"""Pure-Python reference crypto (host-side oracle + low-volume fallback).

Independent from-spec implementations used as the golden oracle for the TPU
kernels and as the host CPU path for low-volume operations (key generation,
signing a node's own consensus messages — one signature per PBFT phase,
mirroring how the reference only *batches* verification, not signing:
TransactionSync.cpp:516-537 batches verify; PBFTCodec.cpp:47 signs singly).

Python ints are arbitrary-precision, which makes these implementations short
and obviously correct — they are the determinism anchor the TPU kernels are
tested against (SURVEY §4: golden-value crypto tests CPU↔TPU).
"""

from __future__ import annotations

import hmac
import hashlib
import os
from dataclasses import dataclass

# ---------------------------------------------------------------------------
# Keccak-256 (Ethereum padding 0x01)
# ---------------------------------------------------------------------------

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_KECCAK_ROT = [0, 1, 62, 28, 27, 36, 44, 6, 55, 20, 3, 10, 43, 25, 39,
               41, 45, 15, 21, 8, 18, 2, 61, 56, 14]
_M64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    r %= 64
    return ((x << r) | (x >> (64 - r))) & _M64


def _keccak_f(lanes: list[int]) -> list[int]:
    a = lanes
    for rc in _KECCAK_RC:
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x + 4) % 5] ^ _rotl64(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64(a[x + 5 * y], _KECCAK_ROT[x + 5 * y])
        a = [
            b[i] ^ ((~b[(i % 5 + 1) % 5 + 5 * (i // 5)]) & _M64
                    & b[(i % 5 + 2) % 5 + 5 * (i // 5)])
            for i in range(25)
        ]
        a[0] ^= rc
    return a


def keccak256(data: bytes) -> bytes:
    rate = 136
    n = len(data)
    padded = bytearray(data)
    padlen = rate - (n % rate)
    padded += b"\x00" * padlen
    padded[n] ^= 0x01
    padded[-1] ^= 0x80
    lanes = [0] * 25
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lanes[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        lanes = _keccak_f(lanes)
    return b"".join(lanes[i].to_bytes(8, "little") for i in range(4))


# ---------------------------------------------------------------------------
# SM3
# ---------------------------------------------------------------------------

_M32 = (1 << 32) - 1


def _rotl32(x: int, r: int) -> int:
    r %= 32
    return ((x << r) | (x >> (32 - r))) & _M32


def sm3(data: bytes) -> bytes:
    iv = [0x7380166F, 0x4914B2B9, 0x172442D7, 0xDA8A0600,
          0xA96F30BC, 0x163138AA, 0xE38DEE4D, 0xB0FB0E4E]
    n = len(data)
    msg = bytearray(data)
    msg.append(0x80)
    while len(msg) % 64 != 56:
        msg.append(0)
    msg += (n * 8).to_bytes(8, "big")
    V = iv
    for off in range(0, len(msg), 64):
        W = [int.from_bytes(msg[off + 4 * i : off + 4 * i + 4], "big") for i in range(16)]
        for j in range(16, 68):
            x = W[j - 16] ^ W[j - 9] ^ _rotl32(W[j - 3], 15)
            W.append((x ^ _rotl32(x, 15) ^ _rotl32(x, 23)) ^ _rotl32(W[j - 13], 7) ^ W[j - 6])
        A, B, C, D, E, F, G, H = V
        for j in range(64):
            Tj = 0x79CC4519 if j < 16 else 0x7A879D8A
            a12 = _rotl32(A, 12)
            SS1 = _rotl32((a12 + E + _rotl32(Tj, j)) & _M32, 7)
            SS2 = SS1 ^ a12
            if j < 16:
                FF, GG = A ^ B ^ C, E ^ F ^ G
            else:
                FF = (A & B) | (A & C) | (B & C)
                GG = (E & F) | ((~E & _M32) & G)
            TT1 = (FF + D + SS2 + (W[j] ^ W[j + 4])) & _M32
            TT2 = (GG + H + SS1 + W[j]) & _M32
            D, C, B, A = C, _rotl32(B, 9), A, TT1
            H, G, F, E = G, _rotl32(F, 19), E, (TT2 ^ _rotl32(TT2, 9) ^ _rotl32(TT2, 17))
        V = [v ^ o for v, o in zip(V, [A, B, C, D, E, F, G, H])]
    return b"".join(v.to_bytes(4, "big") for v in V)


# ---------------------------------------------------------------------------
# Elliptic curves (affine, Python ints)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CurveParams:
    name: str
    p: int
    a: int
    b: int
    n: int
    gx: int
    gy: int


SECP256K1 = CurveParams(
    name="secp256k1",
    p=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F,
    a=0,
    b=7,
    n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)

SM2P256V1 = CurveParams(
    name="sm2p256v1",
    p=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFF,
    a=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFC,
    b=0x28E9FA9E9D9F5E344D5A9E4BCF6509A7F39789F515AB8F92DDBCBD414D940E93,
    n=0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFF7203DF6B21C6052B53BBF40939D54123,
    gx=0x32C4AE2C1F1981195F9904466A39C9948FE30BBFF2660BE1715A4589334C74C7,
    gy=0xBC3736A2F4F6779C59BDCEE36B692153D0A9877CC62A474002DF32E52139F0A0,
)


def is_on_curve(c: CurveParams, P) -> bool:
    """Affine point validity (None = infinity is NOT considered on-curve
    for input validation purposes)."""
    if P is None:
        return False
    x, y = P
    if not (0 <= x < c.p and 0 <= y < c.p):
        return False
    return (y * y - (x * x * x + c.a * x + c.b)) % c.p == 0


def ec_add(c: CurveParams, P, Q):
    if P is None:
        return Q
    if Q is None:
        return P
    x1, y1 = P
    x2, y2 = Q
    if x1 == x2:
        if (y1 + y2) % c.p == 0:
            return None
        lam = (3 * x1 * x1 + c.a) * pow(2 * y1, -1, c.p) % c.p
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, c.p) % c.p
    x3 = (lam * lam - x1 - x2) % c.p
    y3 = (lam * (x1 - x3) - y1) % c.p
    return (x3, y3)


def ec_mul(c: CurveParams, k: int, P):
    R = None
    A = P
    while k:
        if k & 1:
            R = ec_add(c, R, A)
        A = ec_add(c, A, A)
        k >>= 1
    return R


def ec_on_curve(c: CurveParams, P) -> bool:
    if P is None:
        return True
    x, y = P
    return (y * y - (x * x * x + c.a * x + c.b)) % c.p == 0


# ---------------------------------------------------------------------------
# ECDSA (secp256k1) sign / verify / recover — Python-int oracle
# ---------------------------------------------------------------------------

def _rfc6979_k(secret: int, h: bytes, n: int, extra: bytes = b"") -> int:
    """Deterministic nonce (RFC 6979, HMAC-SHA256)."""
    qlen = 32
    V = b"\x01" * 32
    K = b"\x00" * 32
    x = secret.to_bytes(qlen, "big")
    K = hmac.new(K, V + b"\x00" + x + h + extra, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h + extra, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 1 <= k < n:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def ecdsa_sign(c: CurveParams, secret: int, msg_hash: bytes):
    """-> (r, s, v) with v the recovery id (0/1, y-parity of R; low-s form)."""
    e = int.from_bytes(msg_hash, "big") % c.n
    while True:
        k = _rfc6979_k(secret, msg_hash, c.n)
        R = ec_mul(c, k, (c.gx, c.gy))
        r = R[0] % c.n
        if r == 0:
            continue
        s = (pow(k, -1, c.n) * (e + r * secret)) % c.n
        if s == 0:
            continue
        v = R[1] & 1
        if s > c.n // 2:
            s = c.n - s
            v ^= 1
        return r, s, v


def ecdsa_verify(c: CurveParams, pub, msg_hash: bytes, r: int, s: int) -> bool:
    if not (1 <= r < c.n and 1 <= s < c.n) or not ec_on_curve(c, pub) or pub is None:
        return False
    e = int.from_bytes(msg_hash, "big") % c.n
    w = pow(s, -1, c.n)
    u1, u2 = (e * w) % c.n, (r * w) % c.n
    R = ec_add(c, ec_mul(c, u1, (c.gx, c.gy)), ec_mul(c, u2, pub))
    return R is not None and R[0] % c.n == r


def ecdsa_recover(c: CurveParams, msg_hash: bytes, r: int, s: int, v: int):
    """Recover public key from signature; None if invalid."""
    if not (1 <= r < c.n and 1 <= s < c.n):
        return None
    x = r + (v >> 1) * c.n
    if x >= c.p:
        return None
    ysq = (pow(x, 3, c.p) + c.a * x + c.b) % c.p
    y = pow(ysq, (c.p + 1) // 4, c.p)
    if (y * y) % c.p != ysq:
        return None
    if (y & 1) != (v & 1):
        y = c.p - y
    e = int.from_bytes(msg_hash, "big") % c.n
    rinv = pow(r, -1, c.n)
    # Q = r^-1 (s*R - e*G)
    Q = ec_add(
        c,
        ec_mul(c, (s * rinv) % c.n, (x, y)),
        ec_mul(c, (-e * rinv) % c.n, (c.gx, c.gy)),
    )
    return Q


# ---------------------------------------------------------------------------
# SM2 sign / verify (GB/T 32918) — Python-int oracle
# ---------------------------------------------------------------------------

def sm2_sign(secret: int, msg_hash: bytes, k: int | None = None):
    """SM2 signature over a precomputed digest e (the reference signs the
    SM3(Z_A || M) digest computed upstream). -> (r, s)."""
    c = SM2P256V1
    e = int.from_bytes(msg_hash, "big") % c.n
    while True:
        if k is None:
            kk = _rfc6979_k(secret, msg_hash, c.n, extra=b"sm2")
        else:
            kk = k
        P = ec_mul(c, kk, (c.gx, c.gy))
        r = (e + P[0]) % c.n
        if r == 0 or r + kk == c.n:
            k = None
            continue
        s = (pow(1 + secret, -1, c.n) * (kk - r * secret)) % c.n
        if s == 0:
            k = None
            continue
        return r, s


def sm2_verify(pub, msg_hash: bytes, r: int, s: int) -> bool:
    c = SM2P256V1
    if not (1 <= r < c.n and 1 <= s < c.n) or pub is None or not ec_on_curve(c, pub):
        return False
    e = int.from_bytes(msg_hash, "big") % c.n
    t = (r + s) % c.n
    if t == 0:
        return False
    P = ec_add(c, ec_mul(c, s, (c.gx, c.gy)), ec_mul(c, t, pub))
    if P is None:
        return False
    return (e + P[0]) % c.n == r


# ---------------------------------------------------------------------------
# GLV endomorphism (secp256k1) — host oracle for the device decomposition
# ---------------------------------------------------------------------------
# secp256k1 has j-invariant 0 (a = 0, p = 1 mod 3), so phi(x, y) =
# (beta*x, y) is an endomorphism with phi(P) = lambda*P for the matching
# cube roots of unity (beta^3 = 1 mod p, lambda^3 = 1 mod n). Splitting a
# scalar k = k1 + k2*lambda (mod n) with |k1|, |k2| ~ sqrt(n) halves the
# doubling ladder. Constants are the standard public secp256k1 values
# (verified against each other in ec.Curve.__init__); the decomposition is
# the mul-shift form: c_i = floor(k * g_i / 2^384) with g_i =
# round(2^384 * b_i' / n), then k2 = c1*(-b1) + c2*(-b2) mod n and
# k1 = k - k2*lambda mod n — exact by construction, the rounding only
# nudges the (still ~128-bit) magnitudes.

GLV_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
GLV_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
_GLV_MINUS_B1 = 0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_B2 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_MINUS_B2 = (-_GLV_B2) % SECP256K1.n
_GLV_G1 = ((1 << 384) * _GLV_B2 + SECP256K1.n // 2) // SECP256K1.n
_GLV_G2 = ((1 << 384) * _GLV_MINUS_B1 + SECP256K1.n // 2) // SECP256K1.n


def glv_split(k: int, n: int = SECP256K1.n) -> tuple[int, int]:
    """k -> (k1, k2) with k1 + k2*lambda = k (mod n), both in [0, n).

    Mapped to signed form (min(k_i, n - k_i)) the magnitudes are ~2^128.
    """
    c1 = (k * _GLV_G1) >> 384
    c2 = (k * _GLV_G2) >> 384
    k2 = (c1 * _GLV_MINUS_B1 + c2 * _GLV_MINUS_B2) % n
    k1 = (k - k2 * GLV_LAMBDA) % n
    return k1, k2


def keygen(c: CurveParams = SECP256K1, seed: bytes | None = None):
    """-> (secret_int, (pub_x, pub_y)). Seed for deterministic test keys."""
    if seed is not None:
        secret = int.from_bytes(hashlib.sha256(seed).digest(), "big") % (c.n - 1) + 1
    else:
        secret = int.from_bytes(os.urandom(32), "big") % (c.n - 1) + 1
    return secret, ec_mul(c, secret, (c.gx, c.gy))
