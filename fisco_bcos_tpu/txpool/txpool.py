"""TxPool — pending-transaction store with TPU batch validation.

Reference counterpart: /root/reference/bcos-txpool/bcos-txpool/ —
MemoryStorage (txpool/storage/MemoryStorage.cpp:66 submitTransaction, :223
verifyAndSubmitTransaction, :570 batchFetchTxs, :919 batchVerifyProposal) and
TxValidator (txpool/validator/TxValidator.cpp:27-68: nonce/chainId/groupId/
blockLimit checks then the per-tx signature recover at :56).

Design difference (the point of this framework): validation is *batch-first*.
`submit_batch` runs the cheap host checks per tx, then pushes every
still-unverified signature through ONE TPU recover call
(protocol.batch_recover_senders) instead of the reference's
tbb::parallel_for over scalar verifies (TransactionSync.cpp:516-537).
The single-tx `submit` is the degenerate case. Duplicate-nonce tracking
follows the reference's TxPoolNonceChecker: nonces of the last `block_limit`
committed blocks are a rolling filter.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

from ..ledger.ledger import Ledger
from ..protocol import Block, Transaction, TransactionStatus, batch_hash, \
    batch_recover_senders
from ..utils.log import LOG, badge, metric

DEFAULT_POOL_LIMIT = 15000  # txpool.limit default (NodeConfig.cpp:473-493)


@dataclasses.dataclass
class TxSubmitResult:
    tx_hash: bytes
    status: TransactionStatus
    sender: Optional[bytes] = None


class SubmitRejected(RuntimeError):
    """Async submission failed admission; carries the TxSubmitResult."""

    def __init__(self, result: TxSubmitResult):
        super().__init__(f"tx rejected: {result.status!r}")
        self.result = result


class TxPool:
    def __init__(self, suite, ledger: Ledger, chain_id: str = "chain0",
                 group_id: str = "group0", pool_limit: int = DEFAULT_POOL_LIMIT,
                 block_limit_range: int = 600, registry=None):
        self.suite = suite
        self._registry = registry  # None -> utils.metrics.REGISTRY
        self.ledger = ledger
        self.chain_id = chain_id
        self.group_id = group_id
        self.pool_limit = pool_limit
        self.block_limit_range = block_limit_range
        self._lock = threading.RLock()
        self._pending: "OrderedDict[bytes, Transaction]" = OrderedDict()
        self._sealed: set[bytes] = set()  # invariant: subset of _pending
        # pre-seal tombstones: hashes of in-flight proposal txs NOT yet in
        # the pool (see mark_sealed) — promoted to _sealed on arrival
        self._presealed: set[bytes] = set()
        # rolling nonce filter: block number -> set of nonces. Seeded from
        # the ledger at construction: after a WAL-replay restart the
        # filter used to come up EMPTY, so a different-hash tx reusing a
        # nonce committed just before the crash was re-admitted inside
        # the replay-protection window (found by the invariant auditor's
        # nonce_filter check during the crash-failpoint e2e run). The
        # snapshot-install path rebuilds the same way.
        self._nonces_by_block: dict[int, set[str]] = {}
        self._known_nonces: set[str] = set()
        self._rebuild_nonce_filter(self.ledger.current_number())
        self._on_ready: list[Callable[[], None]] = []
        # receipt waits: one condition broadcast per commit. A shared CV
        # (instead of the old per-hash Event dict) survives concurrent
        # waiters on the same hash — with the dict, the first waiter to
        # time out popped the registration and stranded the others — and
        # costs one notify_all per BLOCK, not per waiting RPC thread.
        self._receipt_cv = threading.Condition()
        self._async_waiters: dict[bytes, "object"] = {}  # hash -> Task
        # TransactionSync gossip hook (TransactionSync.cpp broadcast path)
        self._broadcast_hooks: list[Callable[[Sequence[Transaction]], None]] = []

    def _rebuild_nonce_filter(self, number: int) -> None:
        """Rebuild the rolling replay-protection window from the ledger —
        the ONE copy of this loop, shared by boot (no-op on fresh nodes)
        and the snapshot-install reconciliation."""
        self._nonces_by_block = {}
        self._known_nonces = set()
        lo = max(1, number - self.block_limit_range + 1)
        for bn in range(lo, number + 1):
            try:
                ns = set(n for n in self.ledger.nonces_by_number(bn) if n)
            except Exception:  # pruned below a checkpoint floor
                continue
            if ns:
                self._nonces_by_block[bn] = ns
                self._known_nonces |= ns

    # -- notifications -----------------------------------------------------
    def register_unseal_notifier(self, fn: Callable[[], None]) -> None:
        self._on_ready.append(fn)

    def register_broadcast_hook(
            self, fn: Callable[[Sequence[Transaction]], None]) -> None:
        """TransactionSync registers here to gossip newly accepted txs."""
        self._broadcast_hooks.append(fn)

    def _update_pending_gauge(self) -> None:
        """Feed the dashboard's pending-tx panel (tools/monitor)."""
        from ..utils.metrics import REGISTRY
        with self._lock:
            n = len(self._pending) - len(self._sealed)
        (self._registry or REGISTRY).set_gauge("bcos_txpool_pending", n)

    def _notify_ready(self) -> None:
        for fn in self._on_ready:
            try:
                fn()
            except Exception:  # noqa: BLE001 — notifiers run AFTER
                # admission: a raising sealer callback must not surface
                # as a submit failure (the ingest lane's fallback treats
                # submit_batch exceptions as "not admitted")
                LOG.exception(badge("TXPOOL", "ready-notifier-failed"))

    # -- submission --------------------------------------------------------
    def submit(self, tx: Transaction) -> TxSubmitResult:
        return self.submit_batch([tx])[0]

    def submit_batch(self, txs: Sequence[Transaction],
                     broadcast: bool = True) -> list[TxSubmitResult]:
        """Host checks + one TPU batch recover for the survivors."""
        t0 = time.monotonic()
        hashes = batch_hash(txs, self.suite)
        results: list[Optional[TxSubmitResult]] = [None] * len(txs)
        need_verify: list[int] = []
        with self._lock:
            current = self.ledger.current_number()
            seen_batch: set[bytes] = set()
            for i, (tx, h) in enumerate(zip(txs, hashes)):
                st = self._precheck(tx, h, current)
                if st is None and h in seen_batch:
                    st = TransactionStatus.ALREADY_IN_TXPOOL
                if st is not None:
                    results[i] = TxSubmitResult(h, st)
                else:
                    seen_batch.add(h)
                    need_verify.append(i)
        if need_verify:
            sub = [txs[i] for i in need_verify]
            t_rec = time.monotonic()
            _, ok = batch_recover_senders(sub, self.suite)
            # per-batch signature-recover time -> the latency attribution
            # plane's "crypto" stage (covers the lane AND direct paths);
            # unlabeled on purpose — all bcos_tx_stage_seconds stages
            # share one series family so cross-stage shares stay honest
            from ..utils.trace import observe_stage
            observe_stage("crypto", time.monotonic() - t_rec)
            with self._lock:
                for j, i in enumerate(need_verify):
                    tx, h = txs[i], hashes[i]
                    if not ok[j]:
                        results[i] = TxSubmitResult(h, TransactionStatus.INVALID_SIGNATURE)
                        continue
                    if len(self._pending) >= self.pool_limit:
                        results[i] = TxSubmitResult(h, TransactionStatus.TXPOOL_FULL)
                        continue
                    self._pending[h] = tx
                    if h in self._presealed:  # already in an in-flight
                        self._presealed.discard(h)  # proposal: arrive sealed
                        self._sealed.add(h)
                    if tx.nonce:
                        self._known_nonces.add(tx.nonce)
                    results[i] = TxSubmitResult(h, TransactionStatus.OK,
                                                tx.sender(self.suite))
        n_ok = sum(1 for r in results
                   if r.status == TransactionStatus.OK)
        metric("txpool.submit_batch", n=len(txs), ok=n_ok,
               ms=int((time.monotonic() - t0) * 1000))
        # traced submissions: one admission span per sampled tx context
        # (cheap: touched only when a context is actually attached)
        for tx in txs:
            ctx = getattr(tx, "_otrace", None)
            if ctx is not None and ctx.sampled:
                from ..utils import otrace
                otrace.TRACER.record(
                    "txpool.admit", ctx, t0,
                    attrs={"n": len(txs), "ok": n_ok,
                           "group": self.group_id})
        self._update_pending_gauge()
        if need_verify:
            self._notify_ready()
        if broadcast and self._broadcast_hooks:
            accepted = [txs[i] for i, r in enumerate(results)
                        if r.status == TransactionStatus.OK]
            if accepted:
                for fn in self._broadcast_hooks:
                    try:
                        fn(accepted)
                    except Exception:  # noqa: BLE001 — the txs ARE admitted
                        # a gossip-hook failure must not surface as a
                        # submit failure: callers (and the ingest lane's
                        # whole coalesced cohort) would misread an
                        # admitted batch as rejected; anti-entropy
                        # re-gossips what this hook dropped
                        LOG.exception(badge("TXPOOL", "broadcast-hook-failed",
                                            n=len(accepted)))
        return [r for r in results]

    def _precheck(self, tx: Transaction, h: bytes,
                  current: int) -> Optional[TransactionStatus]:
        """Cheap host-side validation (TxValidator.cpp:33-51 semantics)."""
        if h in self._pending or h in self._sealed:
            return TransactionStatus.ALREADY_IN_TXPOOL
        if self.ledger.receipt(h) is not None:
            return TransactionStatus.ALREADY_KNOWN
        if tx.chain_id != self.chain_id:
            return TransactionStatus.INVALID_CHAINID
        if tx.group_id != self.group_id:
            return TransactionStatus.INVALID_GROUPID
        if tx.block_limit <= current or \
                tx.block_limit > current + self.block_limit_range:
            return TransactionStatus.BLOCK_LIMIT_CHECK_FAIL
        if tx.nonce and tx.nonce in self._known_nonces:
            return TransactionStatus.NONCE_CHECK_FAIL
        return None

    # -- sealing (MemoryStorage.cpp:570 batchFetchTxs) ---------------------
    def seal(self, max_txs: int) -> tuple[list[Transaction], list[bytes]]:
        """Fetch up to max_txs unsealed txs, marking them sealed. Re-checks
        block_limit against the current height (a tx can expire while queued;
        the reference re-validates at seal time) and drops expired ones."""
        with self._lock:
            current = self.ledger.current_number()
            out, hashes, expired = [], [], []
            for h, tx in self._pending.items():
                if h in self._sealed:
                    continue
                if tx.block_limit <= current:
                    expired.append(h)
                    continue
                out.append(tx)
                hashes.append(h)
                if len(out) >= max_txs:
                    break
            self._sealed.update(hashes)
            dropped_tasks = []
            for h in expired:
                self._pending.pop(h, None)
                t = self._async_waiters.pop(h, None)
                if t is not None:
                    dropped_tasks.append(t)
        for t in dropped_tasks:  # settle, never leak an expired submission
            t.reject(TimeoutError("tx expired: block_limit passed unsealed"))
        self._update_pending_gauge()
        return out, hashes

    def unseal(self, hashes: Sequence[bytes]) -> None:
        """Return sealed txs to the pool (failed proposal / view change)."""
        with self._lock:
            for h in hashes:
                self._sealed.discard(h)
                self._presealed.discard(h)
        self._update_pending_gauge()
        self._notify_ready()

    def mark_sealed(self, hashes: Sequence[bytes]) -> None:
        """Mark txs as sealed WITHOUT fetching them — consensus calls this
        when accepting a proposal so the local sealer (which may lead a
        later pipelined height) never packs the same txs into a second
        proposal (the reference's asyncMarkTxs(sealed=true) on proposal
        receipt, MemoryStorage.cpp:700).

        A hash not in the pool yet leaves a PRE-SEAL tombstone: if the tx
        arrives later via gossip it enters the pool already sealed, so a
        pipelined next-height proposal can never double-include it (it
        would become unexecutable cluster-wide once the earlier height
        commits and prunes the tx). Tombstones are cleared by commit,
        unseal (view change) or tx arrival."""
        with self._lock:
            for h in hashes:
                if h in self._pending:
                    self._sealed.add(h)
                else:
                    self._presealed.add(h)
        self._update_pending_gauge()

    def pending_txs(self, max_txs: int = 0) -> list[Transaction]:
        """Unsealed pending txs, oldest first (TransactionSync's periodic
        anti-entropy rebroadcast; sealed txs ride their proposal instead)."""
        with self._lock:
            out = [tx for h, tx in self._pending.items()
                   if h not in self._sealed]
        return out[:max_txs] if max_txs else out

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending) - len(self._sealed)

    def status(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending), "sealed": len(self._sealed)}

    def known_nonces(self) -> frozenset:
        """Snapshot of the rolling replay-protection filter — read by the
        invariant auditor (ops/audit.py), which cross-checks it against
        the nonces the ledger actually committed in the window."""
        with self._lock:
            return frozenset(self._known_nonces)

    # -- proposal verification (TxPool.cpp:160 asyncVerifyBlock) -----------
    def fill_block(self, tx_hashes: Sequence[bytes]) -> Optional[list[Transaction]]:
        """hashes -> txs from the pool (BlockExecutive::prepare's
        asyncFillBlock). None if any is missing."""
        with self._lock:
            out = []
            for h in tx_hashes:
                tx = self._pending.get(h)
                if tx is None:
                    return None
                out.append(tx)
            return out

    def missing_hashes(self, hashes: Sequence[bytes]) -> list[bytes]:
        """Subset of `hashes` not present in the pool (fetch-missing path)."""
        with self._lock:
            return [h for h in hashes if h not in self._pending]

    def unknown_hashes(self, hashes: Sequence[bytes]) -> set[bytes]:
        """Subset of `hashes` this node holds NO copy of (not pending and
        not committed) — the gossip import path's decode filter."""
        with self._lock:
            cand = [h for h in hashes if h not in self._pending]
        return {h for h in cand if self.ledger.receipt(h) is None}

    def verify_proposal(self, block: Block) -> bool:
        """Verify a proposal: every tx known (already validated at submit) or,
        if the proposal carries full txs, batch-verify the unknown ones
        (MemoryStorage.cpp:919 batchVerifyProposal)."""
        # batch_hash: txs that rode submit -> seal on this node carry their
        # cached hash; only gossip-fresh ones are hashed, in ONE call
        hashes = block.tx_hashes or batch_hash(block.transactions, self.suite)
        with self._lock:
            missing = [h for h in hashes if h not in self._pending]
        if not missing:
            return True
        if not block.transactions:
            return False
        by_hash = dict(zip(batch_hash(block.transactions, self.suite),
                           block.transactions))
        todo = [by_hash[h] for h in missing if h in by_hash]
        if len(todo) != len(missing):
            return False
        _, ok = batch_recover_senders(todo, self.suite)
        if not bool(np.all(ok)):
            return False
        # import the newly-verified txs so commit can prune them
        with self._lock:
            current = self.ledger.current_number()
            for tx in todo:
                h = tx.hash(self.suite)
                if self._precheck(tx, h, current) is None:
                    self._pending[h] = tx
                    self._sealed.add(h)
                    self._presealed.discard(h)
                    if tx.nonce:
                        self._known_nonces.add(tx.nonce)
        return True

    # -- commit notification (prune + nonce window) ------------------------
    def on_block_committed(self, number: int, tx_hashes: Sequence[bytes],
                           nonces: Sequence[str]) -> None:
        with self._lock:
            for h in tx_hashes:
                self._pending.pop(h, None)
                self._sealed.discard(h)
                self._presealed.discard(h)
            ns = set(n for n in nonces if n)
            self._nonces_by_block[number] = ns
            self._known_nonces.update(ns)
            expired = number - self.block_limit_range
            for bn in [b for b in self._nonces_by_block if b <= expired]:
                self._known_nonces -= self._nonces_by_block.pop(bn)
            tasks = [(h, self._async_waiters.pop(h)) for h in tx_hashes
                     if h in self._async_waiters]
        with self._receipt_cv:
            self._receipt_cv.notify_all()
        for h, task in tasks:
            task.resolve(self.ledger.receipt(h))
        self._update_pending_gauge()
        self._notify_ready()

    def on_snapshot_installed(self, number: int) -> None:
        """The ledger jumped to `number` via a snap-sync install — per-block
        commit notifications never ran for the jumped range. Reconcile:
        drop pending txs the installed state already committed (receipt
        lookup; pruned heights have none, but their txs are long past
        block_limit anyway), rebuild the rolling nonce filter from the
        installed nonce tables, and settle receipt waiters."""
        with self._lock:
            candidates = list(self._pending)
        # receipt probes are storage reads — O(pool) of them must not run
        # under the pool lock (they'd stall every submit/seal for the
        # duration); the pops below re-check membership anyway
        committed = [h for h in candidates
                     if self.ledger.receipt(h) is not None]
        with self._lock:
            for h in committed:
                self._pending.pop(h, None)
                self._sealed.discard(h)
                self._presealed.discard(h)
            self._rebuild_nonce_filter(number)
            # txs that survived the reconciliation are still pending: their
            # nonces were admitted at submit time and must keep blocking
            # duplicates (they are in no block's nonce table yet)
            for tx in self._pending.values():
                if tx.nonce:
                    self._known_nonces.add(tx.nonce)
            tasks = [(h, self._async_waiters.pop(h)) for h in committed
                     if h in self._async_waiters]
        with self._receipt_cv:
            self._receipt_cv.notify_all()
        for h, task in tasks:
            task.resolve(self.ledger.receipt(h))
        self._update_pending_gauge()
        self._notify_ready()

    def submit_async(self, tx: Transaction):
        """Submit and return a Task[Receipt] that settles at commit — the
        libtask analogue of the reference's coroutine submitTransaction
        (Task.h:19-50 awaited at JsonRpcImpl_2_0.cpp:455). Rejected with
        SubmitRejected if admission fails."""
        from ..utils.task import Task

        task: Task = Task()
        res = self.submit(tx)
        if int(res.status) != 0:
            task.reject(SubmitRejected(res))
            return task
        h = res.tx_hash
        rc = self.ledger.receipt(h)
        if rc is not None:
            task.resolve(rc)
            return task
        with self._lock:
            self._async_waiters[h] = task
        rc = self.ledger.receipt(h)  # commit raced the registration
        if rc is not None:
            with self._lock:
                self._async_waiters.pop(h, None)
            task.resolve(rc)
        return task

    # -- RPC receipt waiting ----------------------------------------------
    def wait_for_receipt(self, tx_hash: bytes, timeout: float = 30.0):
        """Block until the tx is committed; -> Receipt or None on timeout.

        Event-driven: parks on `_receipt_cv` (broadcast once per committed
        block from `on_block_committed`) instead of polling the ledger —
        a node under concurrent RPC load must not burn its cores spinning.
        The parked path's receipt check runs WHILE HOLDING the cv lock, so
        a commit that lands between the check and the wait still delivers
        its wakeup (the notifier can't broadcast until the waiter is
        parked); the common already-committed path stays lock-free."""
        rc = self.ledger.receipt(tx_hash)
        if rc is not None:
            return rc
        deadline = time.monotonic() + timeout
        with self._receipt_cv:
            while True:
                rc = self.ledger.receipt(tx_hash)
                if rc is not None:
                    return rc
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._receipt_cv.wait(left)
