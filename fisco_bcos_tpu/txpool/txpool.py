"""TxPool — pending-transaction store with TPU batch validation.

Reference counterpart: /root/reference/bcos-txpool/bcos-txpool/ —
MemoryStorage (txpool/storage/MemoryStorage.cpp:66 submitTransaction, :223
verifyAndSubmitTransaction, :570 batchFetchTxs, :919 batchVerifyProposal) and
TxValidator (txpool/validator/TxValidator.cpp:27-68: nonce/chainId/groupId/
blockLimit checks then the per-tx signature recover at :56).

Design difference (the point of this framework): validation is *batch-first*.
`submit_batch` runs the cheap host checks per tx, then pushes every
still-unverified signature through ONE TPU recover call
(protocol.batch_recover_senders) instead of the reference's
tbb::parallel_for over scalar verifies (TransactionSync.cpp:516-537).
The single-tx `submit` is the degenerate case. Duplicate-nonce tracking
follows the reference's TxPoolNonceChecker: nonces of the last `block_limit`
committed blocks are a rolling filter.

Overload control (the serving-stack watermark discipline): admission is no
longer a hard `TXPOOL_FULL` cliff at `pool_limit`. Below the LOW watermark
everything admits; between the watermarks, band-0 txs must carry enough
remaining `block_limit` lifetime to realistically seal before expiry
(DEADLINE_UNMEETABLE otherwise — admitting them would only burn verify +
pool slots they can never repay); at the HIGH watermark an incoming tx
admits only by EVICTING a strictly lower-priority pending tx
(TXPOOL_EVICTED), so the pool can never wedge full of stale low-value
traffic. Priority = (band, block_limit): the `attribute` word's top byte
is the client-declared priority band (the gas-price-band analogue — this
chain has no fee market), ties broken toward keeping the later-expiring
and younger tx. Capacity/priority verdicts are computed BEFORE the batch
recover, so a congested pool rejects without paying the crypto lane; and
every admitted-then-dropped tx settles its waiters promptly with the
typed status (`TxDropped`) instead of letting clients hang to timeout.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

from ..analysis import lockcheck as lc
from ..ledger.ledger import Ledger
from ..protocol import Block, Transaction, TransactionStatus, batch_hash, \
    batch_recover_senders
from ..utils.log import LOG, badge, metric

DEFAULT_POOL_LIMIT = 15000  # txpool.limit default (NodeConfig.cpp:473-493)


@dataclasses.dataclass
class TxSubmitResult:
    tx_hash: bytes
    status: TransactionStatus
    sender: Optional[bytes] = None


class SubmitRejected(RuntimeError):
    """Async submission failed admission; carries the TxSubmitResult."""

    def __init__(self, result: TxSubmitResult):
        super().__init__(f"tx rejected: {result.status!r}")
        self.result = result


class TxDropped(RuntimeError):
    """An ADMITTED tx left THIS node's pool without committing — evicted
    at the high watermark, shed past its deadline, or expired unsealed.
    Carries the typed status so waiters (wait_for_receipt / submit_async)
    settle with a wire-mappable reason instead of a timeout.

    The verdict is node-local: the tx was gossiped, so a peer may still
    seal and commit it. Clients should poll by hash before acting on the
    drop, and resubmit with a FRESH nonce (the original's stays in the
    replay filter for the window, exactly as after a timeout)."""

    def __init__(self, tx_hash: bytes, status: TransactionStatus):
        super().__init__(
            f"tx dropped: {TransactionStatus(status).name}")
        self.tx_hash = tx_hash
        self.status = status


# drop-reason -> the counter the overload bench/dashboards read
_DROP_METRIC = {
    TransactionStatus.TXPOOL_EVICTED: "bcos_txpool_evicted_total",
    TransactionStatus.DEADLINE_UNMEETABLE:
        "bcos_txpool_deadline_shed_total",
    TransactionStatus.BLOCK_LIMIT_CHECK_FAIL: "bcos_txpool_expired_total",
}


class TxPool:
    # max extra blocks of remaining lifetime a band-0 tx must carry as the
    # pool climbs from the low toward the high watermark (linear ramp)
    DEADLINE_SLACK_BLOCKS = 8
    # bounded memory for the typed drop records waiters settle against
    DROPPED_MAX = 8192

    def __init__(self, suite, ledger: Ledger, chain_id: str = "chain0",
                 group_id: str = "group0", pool_limit: int = DEFAULT_POOL_LIMIT,
                 block_limit_range: int = 600, registry=None,
                 low_watermark: float = 0.7, high_watermark: float = 0.95,
                 priority_bands: bool = True):
        self.suite = suite
        self._registry = registry  # None -> utils.metrics.REGISTRY
        self.ledger = ledger
        self.chain_id = chain_id
        self.group_id = group_id
        self.pool_limit = pool_limit
        # watermark admission (module docstring): fractions of pool_limit,
        # clamped sane — low strictly below high, high at most the limit
        high_watermark = min(1.0, max(0.01, float(high_watermark)))
        low_watermark = min(float(low_watermark), high_watermark)
        self._high_mark = max(1, int(pool_limit * high_watermark))
        self._low_mark = min(max(0, int(pool_limit * low_watermark)),
                             self._high_mark - 1)
        # honor the client-declared priority band (see _band). OFF treats
        # every tx as band 0 (eviction order = deadline/age only) — for
        # operators exposing the edge beyond the consortium's own
        # identified clients, where an unauthenticated band would let any
        # sender evict others' pending txs for free
        self.priority_bands = bool(priority_bands)
        self.block_limit_range = block_limit_range
        self._lock = lc.make_rlock("txpool.state")
        self._pending: "OrderedDict[bytes, Transaction]" = OrderedDict()
        self._sealed: set[bytes] = set()  # invariant: subset of _pending
        # pre-seal tombstones: hashes of in-flight proposal txs NOT yet in
        # the pool (see mark_sealed) — promoted to _sealed on arrival
        self._presealed: set[bytes] = set()
        # rolling nonce filter: block number -> set of nonces. Seeded from
        # the ledger at construction: after a WAL-replay restart the
        # filter used to come up EMPTY, so a different-hash tx reusing a
        # nonce committed just before the crash was re-admitted inside
        # the replay-protection window (found by the invariant auditor's
        # nonce_filter check during the crash-failpoint e2e run). The
        # snapshot-install path rebuilds the same way.
        self._nonces_by_block: dict[int, set[str]] = {}
        self._known_nonces: set[str] = set()
        self._install_nonce_filter(
            self._fetch_nonce_window(self.ledger.current_number()))
        self._on_ready: list[Callable[[], None]] = []
        # receipt waits: one condition broadcast per commit. A shared CV
        # (instead of the old per-hash Event dict) survives concurrent
        # waiters on the same hash — with the dict, the first waiter to
        # time out popped the registration and stranded the others — and
        # costs one notify_all per BLOCK, not per waiting RPC thread.
        self._receipt_cv = lc.make_condition("txpool.receipt")
        self._async_waiters: dict[bytes, "object"] = {}  # hash -> Task
        # typed drop records: hash -> TransactionStatus for txs that were
        # ADMITTED and later evicted/shed/expired — wait_for_receipt and
        # submit_async settle against these promptly instead of timing out
        self._dropped: "OrderedDict[bytes, TransactionStatus]" = \
            OrderedDict()
        # TransactionSync gossip hook (TransactionSync.cpp broadcast path)
        self._broadcast_hooks: list[Callable[[Sequence[Transaction]], None]] = []

    def _fetch_nonce_window(self, number: int) -> dict:
        """Read the rolling replay-protection window from the ledger —
        the ONE copy of this loop, shared by boot (no-op on fresh nodes)
        and the snapshot-install reconciliation. Pure ledger reads:
        callers run this OFF txpool.state (a window of storage lookups
        under the pool's hot lock would stall every submit/seal for the
        duration) and install the result via _install_nonce_filter."""
        by_block: dict[int, set] = {}
        lo = max(1, number - self.block_limit_range + 1)
        for bn in range(lo, number + 1):
            try:
                ns = set(n for n in self.ledger.nonces_by_number(bn) if n)
            except Exception:  # pruned below a checkpoint floor
                continue
            if ns:
                by_block[bn] = ns
        return by_block

    def _install_nonce_filter(self, by_block: dict) -> None:
        """Swap in a prefetched nonce window (txpool.state held)."""
        self._nonces_by_block = by_block
        self._known_nonces = set()
        for ns in by_block.values():
            self._known_nonces |= ns

    # -- notifications -----------------------------------------------------
    def register_unseal_notifier(self, fn: Callable[[], None]) -> None:
        self._on_ready.append(fn)

    def register_broadcast_hook(
            self, fn: Callable[[Sequence[Transaction]], None]) -> None:
        """TransactionSync registers here to gossip newly accepted txs."""
        self._broadcast_hooks.append(fn)

    def _update_pending_gauge(self) -> None:
        """Feed the dashboard's pending-tx panel (tools/monitor)."""
        from ..utils.metrics import REGISTRY
        with self._lock:
            n = len(self._pending) - len(self._sealed)
        (self._registry or REGISTRY).set_gauge("bcos_txpool_pending", n)

    def _notify_ready(self) -> None:
        for fn in self._on_ready:
            try:
                fn()
            except Exception:  # noqa: BLE001 — notifiers run AFTER
                # admission: a raising sealer callback must not surface
                # as a submit failure (the ingest lane's fallback treats
                # submit_batch exceptions as "not admitted")
                LOG.exception(badge("TXPOOL", "ready-notifier-failed"))

    # -- submission --------------------------------------------------------
    def submit(self, tx: Transaction) -> TxSubmitResult:
        return self.submit_batch([tx])[0]

    def submit_batch(self, txs: Sequence[Transaction],
                     broadcast: bool = True,
                     consensus: bool = False) -> list[TxSubmitResult]:
        """Host checks + one TPU batch recover for the survivors.

        Watermark/capacity verdicts run in the PRE-crypto phase: a full or
        congested pool answers TXPOOL_FULL / DEADLINE_UNMEETABLE before
        the batch recover, so rejected load costs zero lane work (the
        Blockchain-Machine shed-at-the-front-end discipline). The insert
        phase re-validates against live state — the lock is dropped across
        the recover — and performs any planned high-watermark evictions.

        `consensus=True` (the fetch-missing import behind proposal
        verification) BYPASSES watermark/capacity admission entirely: a
        saturated replica refusing the leader's proposal txs could not
        prepare and would view-change exactly while overloaded — the
        same stall the p2p layer's protected-frame classes prevent. The
        overshoot is bounded by one proposal's tx count, and the txs
        arrive pre-sealed (mark_sealed tombstones), so they are not
        eviction candidates either."""
        t0 = time.monotonic()
        hashes = batch_hash(txs, self.suite)
        results: list[Optional[TxSubmitResult]] = [None] * len(txs)
        need_verify: list[int] = []
        # ledger reads OUTSIDE txpool.state: with a remote ledger/storage
        # frontend these are RPCs, and even in-process they are GIL-held
        # time every other submitter serialises behind (bcosflow:
        # lock-blocking-interproc on the txpool.state hot lock)
        current = self.ledger.current_number()
        on_chain = [self.ledger.receipt(h) is not None for h in hashes]
        with self._lock:
            seen_batch: set[bytes] = set()
            occupancy = len(self._pending)
            victims: Optional[list] = None
            vi = 0
            for i, (tx, h) in enumerate(zip(txs, hashes)):
                st = self._precheck(tx, h, current, on_chain[i])
                if st is None and h in seen_batch:
                    st = TransactionStatus.ALREADY_IN_TXPOOL
                if st is None and not consensus:
                    if victims is None and occupancy >= min(
                            self._high_mark, self.pool_limit):
                        victims = self._victims_locked()
                    st, _victim, vi, occupancy = self._plan_admission_locked(
                        occupancy, self._band(tx), tx.block_limit, current,
                        victims, vi)
                if st is not None:
                    results[i] = TxSubmitResult(h, st)
                else:
                    seen_batch.add(h)
                    need_verify.append(i)
        drops: list[tuple[bytes, TransactionStatus, object]] = []
        if need_verify:
            sub = [txs[i] for i in need_verify]
            t_rec = time.monotonic()
            senders, ok = batch_recover_senders(sub, self.suite)
            # per-batch signature-recover time -> the latency attribution
            # plane's "crypto" stage (covers the lane AND direct paths);
            # unlabeled on purpose — all bcos_tx_stage_seconds stages
            # share one series family so cross-stage shares stay honest
            from ..utils.trace import observe_stage
            observe_stage("crypto", time.monotonic() - t_rec)
            current = self.ledger.current_number()  # off-lock, as above
            with self._lock:
                occupancy = len(self._pending)
                # the pre-crypto phase's eviction-ordered list carries
                # over: re-sorting ~pool_limit entries under the lock
                # twice per saturated batch was measurable GIL-held time
                # on exactly the hot path. The list may be stale — the
                # lock was dropped across the recover — but the consumer
                # skips entries that left the pool or got sealed, and txs
                # admitted meanwhile are merely missing as candidates
                # (errs toward rejecting the incomer, never toward
                # evicting something protected). Consumption restarts at
                # 0: the pre-phase only SIMULATED its evictions.
                vi = 0
                for j, i in enumerate(need_verify):
                    tx, h = txs[i], hashes[i]
                    if not ok[j]:
                        results[i] = TxSubmitResult(h, TransactionStatus.INVALID_SIGNATURE)
                        continue
                    victim = None
                    if not consensus:
                        if victims is None and occupancy >= min(
                                self._high_mark, self.pool_limit):
                            victims = self._victims_locked()
                        st, victim, vi, occupancy = \
                            self._plan_admission_locked(
                                occupancy, self._band(tx), tx.block_limit,
                                current, victims, vi)
                        if st is not None:
                            results[i] = TxSubmitResult(h, st)
                            continue
                    if victim is not None:
                        # high-watermark exchange: the strictly lower-
                        # priority tx loses its slot to this one
                        task = self._drop_locked(
                            victim, TransactionStatus.TXPOOL_EVICTED)
                        drops.append((victim,
                                      TransactionStatus.TXPOOL_EVICTED,
                                      task))
                    self._pending[h] = tx
                    self._dropped.pop(h, None)  # re-admission voids a
                    #                             stale drop record
                    if h in self._presealed:  # already in an in-flight
                        self._presealed.discard(h)  # proposal: arrive sealed
                        self._sealed.add(h)
                    if tx.nonce:
                        self._known_nonces.add(tx.nonce)
                    # the batch recover above already produced the
                    # sender — re-deriving via tx.sender(suite) under
                    # txpool.state puts a suite_batch recover on the
                    # hot lock's worst-case path (cache miss = crypto
                    # under the lock every submitter waits on)
                    results[i] = TxSubmitResult(h, TransactionStatus.OK,
                                                senders[j])
        self._settle_dropped(drops)
        n_ok = sum(1 for r in results
                   if r.status == TransactionStatus.OK)
        metric("txpool.submit_batch", n=len(txs), ok=n_ok,
               ms=int((time.monotonic() - t0) * 1000))
        # traced submissions: one admission span per sampled tx context
        # (cheap: touched only when a context is actually attached)
        for tx in txs:
            ctx = getattr(tx, "_otrace", None)
            if ctx is not None and ctx.sampled:
                from ..utils import otrace
                otrace.TRACER.record(
                    "txpool.admit", ctx, t0,
                    attrs={"n": len(txs), "ok": n_ok,
                           "group": self.group_id})
        self._update_pending_gauge()
        if need_verify:
            self._notify_ready()
        if broadcast and self._broadcast_hooks:
            accepted = [txs[i] for i, r in enumerate(results)
                        if r.status == TransactionStatus.OK]
            if accepted:
                for fn in self._broadcast_hooks:
                    try:
                        fn(accepted)
                    except Exception:  # noqa: BLE001 — the txs ARE admitted
                        # a gossip-hook failure must not surface as a
                        # submit failure: callers (and the ingest lane's
                        # whole coalesced cohort) would misread an
                        # admitted batch as rejected; anti-entropy
                        # re-gossips what this hook dropped
                        LOG.exception(badge("TXPOOL", "broadcast-hook-failed",
                                            n=len(accepted)))
        return [r for r in results]

    def submit_columns(self, cols, broadcast: bool = True
                       ) -> list[TxSubmitResult]:
        """Columnar admission: the wire-ingest hot path (ROADMAP item 1).

        Mirrors `submit_batch`'s two phases — pre-crypto prechecks +
        watermark planning under the lock, ONE batched recover off it,
        insert phase re-validating against live state — but every check
        reads straight off the column arrays (`protocol.columnar`): no
        `Transaction` construction, no per-field bytes copies, no Reader
        walks. Hashing is one `hash_batch` over arena slices and recovery
        is one `recover_addresses` over the batch; the only per-row
        Python object the path allocates is the lazy `TxView` for rows
        that actually ADMIT (rejected rows never materialise anything).

        Per-slice failure isolation: rows whose frames failed decode
        reject as REQUEST_NOT_BELIEVABLE (tx_hash left empty — there is
        no trustworthy identity to report), rows with bad signatures
        reject INVALID_SIGNATURE, and neither poisons its batchmates."""
        t0 = time.monotonic()
        n = len(cols)
        results: list[Optional[TxSubmitResult]] = [None] * n
        rows: list[int] = []
        for i in range(n):
            if cols.decode_ok[i]:
                rows.append(i)
            else:
                results[i] = TxSubmitResult(
                    b"", TransactionStatus.REQUEST_NOT_BELIEVABLE)
        hashes = cols.ensure_hashes(self.suite)
        from ..utils.trace import observe_stage
        # ledger reads OUTSIDE txpool.state (same rationale as
        # submit_batch: GIL-held / possibly-RPC work off the hot lock)
        current = self.ledger.current_number()
        on_chain = {i: self.ledger.receipt(hashes[i]) is not None
                    for i in rows}
        need_verify: list[int] = []
        with self._lock:
            seen_batch: set[bytes] = set()
            occupancy = len(self._pending)
            victims: Optional[list] = None
            vi = 0
            for i in rows:
                h = hashes[i]
                st = self._precheck_fields(
                    h, cols.chain_id[i], cols.group_id[i],
                    int(cols.block_limit[i]), cols.nonce[i], current,
                    on_chain[i])
                if st is None and h in seen_batch:
                    st = TransactionStatus.ALREADY_IN_TXPOOL
                if st is None:
                    if victims is None and occupancy >= min(
                            self._high_mark, self.pool_limit):
                        victims = self._victims_locked()
                    st, _victim, vi, occupancy = self._plan_admission_locked(
                        occupancy, self._band_attr(int(cols.attribute[i])),
                        int(cols.block_limit[i]), current, victims, vi)
                if st is not None:
                    results[i] = TxSubmitResult(h, st)
                else:
                    seen_batch.add(h)
                    need_verify.append(i)
        drops: list[tuple[bytes, TransactionStatus, object]] = []
        accepted: list = []
        if need_verify:
            t_rec = time.monotonic()
            ok_mask = cols.ensure_senders(self.suite, rows=need_verify)
            observe_stage("crypto", time.monotonic() - t_rec)
            current = self.ledger.current_number()  # off-lock, as above
            with self._lock:
                occupancy = len(self._pending)
                vi = 0  # stale-list carryover: see submit_batch
                for i in need_verify:
                    h = hashes[i]
                    if not ok_mask[i]:
                        results[i] = TxSubmitResult(
                            h, TransactionStatus.INVALID_SIGNATURE)
                        continue
                    if victims is None and occupancy >= min(
                            self._high_mark, self.pool_limit):
                        victims = self._victims_locked()
                    st, victim, vi, occupancy = self._plan_admission_locked(
                        occupancy, self._band_attr(int(cols.attribute[i])),
                        int(cols.block_limit[i]), current, victims, vi)
                    if st is not None:
                        results[i] = TxSubmitResult(h, st)
                        continue
                    if victim is not None:
                        task = self._drop_locked(
                            victim, TransactionStatus.TXPOOL_EVICTED)
                        drops.append((victim,
                                      TransactionStatus.TXPOOL_EVICTED,
                                      task))
                    # the FIRST (and only) per-row object on this path:
                    # the pool's pending map holds tx-shaped things, and
                    # everything downstream of admission (seal, execute,
                    # prewrite, gossip re-encode) runs on the lazy view
                    v = cols.view(i)
                    self._pending[h] = v
                    self._dropped.pop(h, None)
                    if h in self._presealed:
                        self._presealed.discard(h)
                        self._sealed.add(h)
                    if cols.nonce[i]:
                        self._known_nonces.add(cols.nonce[i])
                    accepted.append(v)
                    results[i] = TxSubmitResult(h, TransactionStatus.OK,
                                                cols.senders[i])
        self._settle_dropped(drops)
        metric("txpool.submit_columns", n=n, ok=len(accepted),
               ms=int((time.monotonic() - t0) * 1000))
        self._update_pending_gauge()
        if need_verify:
            self._notify_ready()
        if broadcast and accepted and self._broadcast_hooks:
            for fn in self._broadcast_hooks:
                try:
                    fn(accepted)
                except Exception:  # noqa: BLE001 — same contract as
                    # submit_batch: admitted txs must not read as rejected
                    LOG.exception(badge("TXPOOL", "broadcast-hook-failed",
                                        n=len(accepted)))
        return [r for r in results]

    def _precheck(self, tx: Transaction, h: bytes, current: int,
                  on_chain: bool) -> Optional[TransactionStatus]:
        """Cheap host-side validation (TxValidator.cpp:33-51 semantics).

        `on_chain` is the ledger dup-check verdict, computed by the
        caller BEFORE acquiring txpool.state: the ledger read may be a
        storage lookup (or, split-service, an RPC) and must not run
        under the pool's hot lock."""
        return self._precheck_fields(h, tx.chain_id, tx.group_id,
                                     tx.block_limit, tx.nonce, current,
                                     on_chain)

    def _precheck_fields(self, h: bytes, chain_id: str, group_id: str,
                         block_limit: int, nonce: str, current: int,
                         on_chain: bool) -> Optional[TransactionStatus]:
        """Scalar-argument core of `_precheck`: the columnar path calls
        this straight off the column arrays, so a rejected row never
        materialises a tx object at all."""
        if h in self._pending or h in self._sealed:
            return TransactionStatus.ALREADY_IN_TXPOOL
        if on_chain:
            return TransactionStatus.ALREADY_KNOWN
        if chain_id != self.chain_id:
            return TransactionStatus.INVALID_CHAINID
        if group_id != self.group_id:
            return TransactionStatus.INVALID_GROUPID
        if block_limit <= current or \
                block_limit > current + self.block_limit_range:
            return TransactionStatus.BLOCK_LIMIT_CHECK_FAIL
        if nonce and nonce in self._known_nonces:
            return TransactionStatus.NONCE_CHECK_FAIL
        return None

    # -- watermark admission (overload control) ----------------------------
    def _band(self, tx: Transaction) -> int:
        """Client-declared priority band: the `attribute` word's top byte
        (0-255, default 0). The gas-price-band analogue — this chain has
        no fee market, so priority rides the tx attribute instead.

        TRUST MODEL: the byte is unauthenticated wire data. On a
        permissioned consortium chain (this chain's deployment shape) it
        is a cooperative QoS signal among identified clients — an abuser
        is an access-control problem, and per-client edge budgets
        (rpc/admission.py) bound what any one identity can push. An
        operator exposing the edge to unidentified traffic should set
        `[txpool] priority_bands = false` (bands ignored, eviction by
        deadline/age only), because a forged band-255 flood could
        otherwise evict other clients' pending txs for free."""
        return self._band_attr(tx.attribute)

    def _band_attr(self, attribute: int) -> int:
        """`_band` off the raw attribute word — the columnar path reads
        it straight from the attribute column."""
        if not self.priority_bands:
            return 0
        return (attribute >> 24) & 0xFF

    def _victims_locked(self) -> list:
        """Unsealed pending txs in eviction order — ascending
        (band, block_limit): lowest priority band first, then the
        soonest-expiring, insertion order breaking ties (sort stability
        over the OrderedDict scan keeps the OLDEST first). Sealed txs are
        untouchable: they ride in-flight proposals."""
        return sorted(((self._band(t), t.block_limit, h)
                       for h, t in self._pending.items()
                       if h not in self._sealed),
                      key=lambda v: (v[0], v[1]))

    def _plan_admission_locked(self, occupancy: int, band: int,
                               block_limit: int, current: int,
                               victims: Optional[list], vi: int):
        """One candidate's watermark verdict, off scalar (band,
        block_limit) so the columnar path feeds it straight from columns.
        -> (status|None, victim_hash|None, vi, occupancy).

        `victims` is the lazily built eviction-ordered list (None while
        the pool is below the high watermark), consumed through `vi` so a
        batch's planned evictions never target the same victim twice.
        Pure decision in the pre-crypto phase (victim ignored); in the
        insert phase the returned victim is actually evicted. Freshly
        inserted batch members are not candidates — the scan predates
        them, which only errs toward keeping the newest txs."""
        high = min(self._high_mark, self.pool_limit)
        if occupancy >= high:
            if victims is not None:
                while vi < len(victims) and (
                        victims[vi][2] not in self._pending
                        or victims[vi][2] in self._sealed):
                    vi += 1  # went stale since the scan (committed/sealed)
                if vi < len(victims) \
                        and victims[vi][:2] < (band, block_limit):
                    # strictly lower priority pending: exchange slots
                    return None, victims[vi][2], vi + 1, occupancy
            return TransactionStatus.TXPOOL_FULL, None, vi, occupancy
        if occupancy >= self._low_mark and band == 0:
            # between the watermarks: band-0 txs must carry enough
            # remaining lifetime to realistically seal before expiry —
            # the required slack ramps with congestion
            frac = (occupancy - self._low_mark) / max(
                1, high - self._low_mark)
            required = 1 + int(self.DEADLINE_SLACK_BLOCKS * frac)
            if block_limit - current < required:
                return (TransactionStatus.DEADLINE_UNMEETABLE, None, vi,
                        occupancy)
        return None, None, vi, occupancy + 1

    def _drop_locked(self, h: bytes, status: TransactionStatus):
        """Remove a pending tx for a TYPED reason and record it so waiters
        settle promptly. Caller holds the lock; the returned async task
        (if any) must be rejected OUTSIDE it (via _settle_dropped).

        The nonce is NOT freed: a drop is NODE-LOCAL and the tx was
        already gossiped — a peer may still seal and commit it, so
        re-admitting the same nonce here would break replay protection
        (two same-nonce txs landing in different blocks). Resubmission
        after a drop uses a FRESH nonce, exactly like after a timeout."""
        self._pending.pop(h, None)
        self._sealed.discard(h)
        self._presealed.discard(h)
        self._dropped[h] = status
        while len(self._dropped) > self.DROPPED_MAX:
            self._dropped.popitem(last=False)
        return self._async_waiters.pop(h, None)

    def _settle_dropped(self, drops: list) -> None:
        """Post-lock half of a drop: metrics, receipt-waiter wakeup, async
        task rejection with the typed TxDropped."""
        if not drops:
            return
        from ..utils.metrics import REGISTRY
        reg = self._registry or REGISTRY
        for _h, status, _task in drops:
            name = _DROP_METRIC.get(status)
            if name:
                reg.inc(name)
        with self._receipt_cv:
            self._receipt_cv.notify_all()
        for h, status, task in drops:
            if task is not None:
                task.reject(TxDropped(h, status))

    def dropped_status(self, tx_hash: bytes) -> Optional[TransactionStatus]:
        """Typed reason a formerly admitted tx left the pool uncommitted
        (None when unknown/still pending/committed)."""
        with self._lock:
            return self._dropped.get(tx_hash)

    def occupancy_fraction(self) -> float:
        """Pool fill against the HIGH watermark (~1.0 = eviction
        territory) — the overload controller's txpool signal."""
        with self._lock:
            return len(self._pending) / max(1, self._high_mark)

    # -- sealing (MemoryStorage.cpp:570 batchFetchTxs) ---------------------
    def seal(self, max_txs: int, for_number: Optional[int] = None
             ) -> tuple[list[Transaction], list[bytes]]:
        """Fetch up to max_txs unsealed txs, marking them sealed. Re-checks
        block_limit against the height the proposal will OCCUPY
        (`for_number`; committed+1 when the caller doesn't know) — a tx
        whose limit falls below it would be expired inside its own block,
        so it is dropped with the typed expiry status BEFORE consuming a
        seal slot (with pipelining, proposals run ahead of the committed
        height, so checking only `current` let near-deadline txs burn
        verify + seal work and then expire anyway)."""
        drops: list = []
        current = self.ledger.current_number()  # ledger read off-lock
        with self._lock:
            threshold = for_number if for_number is not None else current + 1
            out, hashes, expired = [], [], []
            for h, tx in self._pending.items():
                if h in self._sealed:
                    continue
                if tx.block_limit < threshold:
                    expired.append(h)
                    continue
                out.append(tx)
                hashes.append(h)
                if len(out) >= max_txs:
                    break
            self._sealed.update(hashes)
            for h in expired:
                task = self._drop_locked(
                    h, TransactionStatus.BLOCK_LIMIT_CHECK_FAIL)
                drops.append((h, TransactionStatus.BLOCK_LIMIT_CHECK_FAIL,
                              task))
        self._settle_dropped(drops)  # never leak an expired submission
        self._update_pending_gauge()
        return out, hashes

    def unseal(self, hashes: Sequence[bytes]) -> None:
        """Return sealed txs to the pool (failed proposal / view change)."""
        with self._lock:
            for h in hashes:
                self._sealed.discard(h)
                self._presealed.discard(h)
        self._update_pending_gauge()
        self._notify_ready()

    def mark_sealed(self, hashes: Sequence[bytes]) -> None:
        """Mark txs as sealed WITHOUT fetching them — consensus calls this
        when accepting a proposal so the local sealer (which may lead a
        later pipelined height) never packs the same txs into a second
        proposal (the reference's asyncMarkTxs(sealed=true) on proposal
        receipt, MemoryStorage.cpp:700).

        A hash not in the pool yet leaves a PRE-SEAL tombstone: if the tx
        arrives later via gossip it enters the pool already sealed, so a
        pipelined next-height proposal can never double-include it (it
        would become unexecutable cluster-wide once the earlier height
        commits and prunes the tx). Tombstones are cleared by commit,
        unseal (view change) or tx arrival."""
        with self._lock:
            for h in hashes:
                if h in self._pending:
                    self._sealed.add(h)
                else:
                    self._presealed.add(h)
        self._update_pending_gauge()

    def pending_txs(self, max_txs: int = 0) -> list[Transaction]:
        """Unsealed pending txs, oldest first (TransactionSync's periodic
        anti-entropy rebroadcast; sealed txs ride their proposal instead)."""
        with self._lock:
            out = [tx for h, tx in self._pending.items()
                   if h not in self._sealed]
        return out[:max_txs] if max_txs else out

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending) - len(self._sealed)

    def status(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending),
                    "sealed": len(self._sealed),
                    "lowWatermark": self._low_mark,
                    "highWatermark": self._high_mark,
                    "dropped": len(self._dropped)}

    def known_nonces(self) -> frozenset:
        """Snapshot of the rolling replay-protection filter — read by the
        invariant auditor (ops/audit.py), which cross-checks it against
        the nonces the ledger actually committed in the window."""
        with self._lock:
            return frozenset(self._known_nonces)

    # -- proposal verification (TxPool.cpp:160 asyncVerifyBlock) -----------
    def fill_block(self, tx_hashes: Sequence[bytes]) -> Optional[list[Transaction]]:
        """hashes -> txs from the pool (BlockExecutive::prepare's
        asyncFillBlock). None if any is missing."""
        with self._lock:
            out = []
            for h in tx_hashes:
                tx = self._pending.get(h)
                if tx is None:
                    return None
                out.append(tx)
            return out

    def missing_hashes(self, hashes: Sequence[bytes]) -> list[bytes]:
        """Subset of `hashes` not present in the pool (fetch-missing path)."""
        with self._lock:
            return [h for h in hashes if h not in self._pending]

    def unknown_hashes(self, hashes: Sequence[bytes]) -> set[bytes]:
        """Subset of `hashes` this node holds NO copy of (not pending and
        not committed) — the gossip import path's decode filter."""
        with self._lock:
            cand = [h for h in hashes if h not in self._pending]
        return {h for h in cand if self.ledger.receipt(h) is None}

    def verify_proposal(self, block: Block) -> bool:
        """Verify a proposal: every tx known (already validated at submit) or,
        if the proposal carries full txs, batch-verify the unknown ones
        (MemoryStorage.cpp:919 batchVerifyProposal)."""
        # batch_hash: txs that rode submit -> seal on this node carry their
        # cached hash; only gossip-fresh ones are hashed, in ONE call
        hashes = block.tx_hashes or batch_hash(block.transactions, self.suite)
        with self._lock:
            missing = [h for h in hashes if h not in self._pending]
        if not missing:
            return True
        if not block.transactions:
            return False
        by_hash = dict(zip(batch_hash(block.transactions, self.suite),
                           block.transactions))
        todo = [by_hash[h] for h in missing if h in by_hash]
        if len(todo) != len(missing):
            return False
        _, ok = batch_recover_senders(todo, self.suite)
        if not bool(np.all(ok)):
            return False
        # import the newly-verified txs so commit can prune them; the
        # ledger reads and hashing stay OFF the txpool.state hot lock
        todo_hashes = batch_hash(todo, self.suite)
        current = self.ledger.current_number()
        todo_known = [self.ledger.receipt(h) is not None
                      for h in todo_hashes]
        with self._lock:
            for tx, h, known in zip(todo, todo_hashes, todo_known):
                if self._precheck(tx, h, current, known) is None:
                    self._pending[h] = tx
                    self._sealed.add(h)
                    self._presealed.discard(h)
                    if tx.nonce:
                        self._known_nonces.add(tx.nonce)
        return True

    # -- commit notification (prune + nonce window) ------------------------
    def on_block_committed(self, number: int, tx_hashes: Sequence[bytes],
                           nonces: Sequence[str]) -> None:
        with self._lock:
            for h in tx_hashes:
                self._pending.pop(h, None)
                self._sealed.discard(h)
                self._presealed.discard(h)
            ns = set(n for n in nonces if n)
            self._nonces_by_block[number] = ns
            self._known_nonces.update(ns)
            expired = number - self.block_limit_range
            for bn in [b for b in self._nonces_by_block if b <= expired]:
                self._known_nonces -= self._nonces_by_block.pop(bn)
            tasks = [(h, self._async_waiters.pop(h)) for h in tx_hashes
                     if h in self._async_waiters]
        with self._receipt_cv:
            self._receipt_cv.notify_all()
        for h, task in tasks:
            task.resolve(self.ledger.receipt(h))
        self._update_pending_gauge()
        self._notify_ready()

    def on_snapshot_installed(self, number: int) -> None:
        """The ledger jumped to `number` via a snap-sync install — per-block
        commit notifications never ran for the jumped range. Reconcile:
        drop pending txs the installed state already committed (receipt
        lookup; pruned heights have none, but their txs are long past
        block_limit anyway), rebuild the rolling nonce filter from the
        installed nonce tables, and settle receipt waiters."""
        with self._lock:
            candidates = list(self._pending)
        # receipt probes are storage reads — O(pool) of them must not run
        # under the pool lock (they'd stall every submit/seal for the
        # duration); the pops below re-check membership anyway
        committed = [h for h in candidates
                     if self.ledger.receipt(h) is not None]
        nonce_window = self._fetch_nonce_window(number)  # off-lock too
        with self._lock:
            for h in committed:
                self._pending.pop(h, None)
                self._sealed.discard(h)
                self._presealed.discard(h)
            self._install_nonce_filter(nonce_window)
            # txs that survived the reconciliation are still pending: their
            # nonces were admitted at submit time and must keep blocking
            # duplicates (they are in no block's nonce table yet)
            for tx in self._pending.values():
                if tx.nonce:
                    self._known_nonces.add(tx.nonce)
            tasks = [(h, self._async_waiters.pop(h)) for h in committed
                     if h in self._async_waiters]
        with self._receipt_cv:
            self._receipt_cv.notify_all()
        for h, task in tasks:
            task.resolve(self.ledger.receipt(h))
        self._update_pending_gauge()
        self._notify_ready()

    def submit_async(self, tx: Transaction):
        """Submit and return a Task[Receipt] that settles at commit — the
        libtask analogue of the reference's coroutine submitTransaction
        (Task.h:19-50 awaited at JsonRpcImpl_2_0.cpp:455). Rejected with
        SubmitRejected if admission fails."""
        from ..utils.task import Task

        task: Task = Task()
        res = self.submit(tx)
        if int(res.status) != 0:
            task.reject(SubmitRejected(res))
            return task
        h = res.tx_hash
        rc = self.ledger.receipt(h)
        if rc is not None:
            task.resolve(rc)
            return task
        with self._lock:
            self._async_waiters[h] = task
        rc = self.ledger.receipt(h)  # commit raced the registration
        if rc is not None:
            with self._lock:
                self._async_waiters.pop(h, None)
            task.resolve(rc)
            return task
        st = self.dropped_status(h)  # ...and so can a drop (seal expiry /
        if st is not None:           # eviction): a _drop_locked that ran
            with self._lock:         # before the registration above
                popped = self._async_waiters.pop(h, None)  # popped no
            if popped is not None:   # waiter — settle it here; if the
                popped.reject(TxDropped(h, st))  # drop path raced us and
                #                      took the task, it settles it itself
        return task

    # -- RPC receipt waiting ----------------------------------------------
    def wait_for_receipt(self, tx_hash: bytes, timeout: float = 30.0):
        """Block until the tx is committed; -> Receipt or None on timeout.
        Raises TxDropped the moment the pool records the tx as evicted/
        shed/expired — a client must not hang to its full timeout for a tx
        that can no longer commit (the drop path broadcasts the same CV).

        Event-driven: parks on `_receipt_cv` (broadcast once per committed
        block from `on_block_committed`) instead of polling the ledger —
        a node under concurrent RPC load must not burn its cores spinning.
        The parked path's receipt check runs WHILE HOLDING the cv lock, so
        a commit that lands between the check and the wait still delivers
        its wakeup (the notifier can't broadcast until the waiter is
        parked); the common already-committed path stays lock-free."""
        rc = self.ledger.receipt(tx_hash)
        if rc is not None:
            return rc
        deadline = time.monotonic() + timeout
        with self._receipt_cv:
            while True:
                rc = self.ledger.receipt(tx_hash)
                if rc is not None:
                    return rc
                st = self.dropped_status(tx_hash)
                if st is not None:  # receipt checked FIRST: a committed
                    raise TxDropped(tx_hash, st)  # tx always wins
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._receipt_cv.wait(left)
