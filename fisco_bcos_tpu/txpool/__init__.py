"""Transaction pool: pending store + batch validator (bcos-txpool)."""

from .ingest import IngestLane, LaneStopped, TxPoolIsFull
from .txpool import TxPool, TxSubmitResult

__all__ = ["IngestLane", "LaneStopped", "TxPool", "TxPoolIsFull",
           "TxSubmitResult"]
