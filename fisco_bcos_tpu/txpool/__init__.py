"""Transaction pool: pending store + batch validator (bcos-txpool)."""

from .txpool import TxPool, TxSubmitResult

__all__ = ["TxPool", "TxSubmitResult"]
