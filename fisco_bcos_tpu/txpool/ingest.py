"""IngestLane — continuous-batching front door for the txpool.

The framework's thesis is batch-first validation (`TxPool.submit_batch`
-> ONE device recover per packet), yet the serving edge defeats it when
every JSON-RPC `sendTransaction` calls `submit(tx)` — a batch of one —
so each independent client pays a full recover (~162 us native; device
amortization needs hundreds of lanes to win, see PERF.md). Hardware
validators get their wins exactly by aggregating independent submissions
in front of the verify engine (Blockchain Machine, arXiv:2104.06968;
FPGA ECDSA engine, arXiv:2112.02229); inference servers call the same
shape continuous batching. This lane is that aggregation layer:

  * concurrent submitters enqueue (tx, future) into a BOUNDED queue —
    a full queue rejects with `TxPoolIsFull` instead of growing without
    bound (admission control, not buffering);
  * one dispatcher thread drains up to `max_batch` txs per cycle and
    issues ONE `TxPool.submit_batch` for the drained set, resolving each
    submitter's future with its per-tx result;
  * the coalescing window is ADAPTIVE: near-zero when idle (a lone tx is
    dispatched immediately, no latency tax), growing toward
    `max_wait_ms` as the arrival rate rises, and sized against the
    crypto suite's padding buckets (crypto.suite.BUCKETS) so drained
    batches land on compiled-executable boundaries instead of paying a
    bucket's padding for a handful of txs.

Producers wired through the lane: `rpc/server.py` send_transaction (HTTP
and WS share `JsonRpcImpl`), `net/txsync.py` gossip ingestion, and the
in-process `Node.send_transaction` surface. `TransactionSync.fetch_missing`
stays on the direct `submit_batch` path: it already holds a full batch and
needs its results synchronously inside proposal verification.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Sequence

from ..analysis import lockcheck as lc
from ..protocol import Transaction
from ..utils import otrace
from ..utils.log import LOG, badge, metric
from ..utils.metrics import REGISTRY
from ..utils.task import Task
from ..utils.trace import observe_stage
from .txpool import TxSubmitResult

from ..crypto.suite import BUCKETS as _SUITE_BUCKETS

# batch-size histogram / coalescing-target buckets: derived from the
# suite's padding buckets so the lane tracks any retuning of the
# compiled-executable grid (1 prepended: a lone idle tx is its own batch)
_SIZE_BUCKETS = (1,) + tuple(_SUITE_BUCKETS)


class TxPoolIsFull(RuntimeError):
    """Ingest queue at capacity — backpressure, not an internal error.

    Carries no result object: the tx never entered admission. RPC maps it
    to TransactionStatus.TXPOOL_FULL for wire compatibility."""


class LaneStopped(RuntimeError):
    """Submission raced the lane's shutdown. Distinct from arbitrary
    dispatch errors so callers can fall back to the direct pool path
    WITHOUT mistaking an already-admitted batch's failure for it."""


class _Entry:
    __slots__ = ("tx", "task", "t_enq", "ctx", "wire")

    def __init__(self, tx: Optional[Transaction], task: Optional[Task],
                 ctx=None, wire: Optional[bytes] = None):
        self.tx = tx  # None: columnar entry — raw frame in `wire`, never
        #               decoded into a Transaction (protocol.columnar)
        self.task = task  # None: fire-and-forget (gossip), nobody awaits
        self.t_enq = time.monotonic()
        # otrace span context of the submitting trace (None when the
        # submission isn't traced): the dispatcher records this entry's
        # queue-to-admission span under it, and one batch span LINKS all
        # coalesced traces
        self.ctx = ctx
        self.wire = wire


class IngestLane:
    """Coalesces concurrent single-tx submissions into device-sized
    `submit_batch` calls. Thread-safe; one dispatcher thread."""

    def __init__(self, txpool, max_batch: int = 4096,
                 max_wait_ms: float = 15.0, queue_cap: int = 8192,
                 broadcast: bool = True, registry=None,
                 trace_label: str = ""):
        self.txpool = txpool
        self.trace_label = trace_label  # span node attribution
        # metrics sink: a multi-group node passes a group-labeled view
        # (utils.metrics.for_group) so G lanes don't silently aggregate
        self._reg = registry if registry is not None else REGISTRY
        self.max_batch = max(1, int(max_batch))
        self.max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self.queue_cap = max(1, int(queue_cap))
        self.broadcast = broadcast
        self._q: deque[_Entry] = deque()
        self._cv = lc.make_condition("ingest.queue")
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # EWMA arrival rate (txs/sec) and mean dispatched batch size,
        # updated once per dispatch cycle — steer the coalescing window
        # without per-enqueue bookkeeping. The batch EWMA is the
        # closed-loop load signal: concurrent submitters each have at
        # most one tx in flight, so a depressed arrival RATE can coexist
        # with heavy concurrency (every submitter blocked on a dispatch),
        # and batches > 1 are the reliable tell.
        self._rate = 0.0
        self._batch_ewma = 1.0
        # EWMA of the intra-batch arrival gap (spread between a batch's
        # first and last enqueue over its size): the quiesce threshold is
        # "a few typical gaps of silence", so tightly-clustered closed-loop
        # cohorts dispatch within ~ms of assembling while slow open-loop
        # trickles still coalesce over the patient window
        self._gap_ewma = 0.0
        self._last_dispatch = time.monotonic()
        # totals for stats()/bench (the registry mirrors them as metrics)
        self._txs_total = 0
        self._batches_total = 0
        self._rejected_total = 0
        self._dropped_total = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = False
        self._thread = threading.Thread(target=self._run, name="tx-ingest",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop the dispatcher, draining the queue first so no submitter is
        left holding an unsettled future."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # wedged dispatcher (e.g. stuck inside submit_batch):
                # keep the reference so a later start() can't spawn a
                # SECOND dispatcher over the same queue — the lane stays
                # stopped and callers use their direct-path fallbacks
                LOG.error(badge("INGEST", "dispatcher-wedged-at-stop"))
                return
            self._thread = None
        # anything still queued (dispatcher died / join timed out): reject
        with self._cv:
            leftovers = list(self._q)
            self._q.clear()
        for e in leftovers:
            if e.task is not None:
                e.task.reject(LaneStopped("ingest lane stopped"))

    # -- producer API ------------------------------------------------------
    def submit_async(self, tx: Transaction) -> Task:
        """Enqueue one tx; -> Task[TxSubmitResult]. Raises TxPoolIsFull
        when the queue is at capacity (bounded-memory backpressure)."""
        ctx = getattr(tx, "_otrace", None) or otrace.current()
        entry = _Entry(tx, Task(), ctx=ctx)
        with self._cv:
            if self._stop:
                raise LaneStopped("ingest lane stopped")
            if len(self._q) >= self.queue_cap:
                self._rejected_total += 1
                self._reg.inc("bcos_ingest_rejected_total")
                raise TxPoolIsFull(
                    f"ingest queue at capacity ({self.queue_cap})")
            self._q.append(entry)
            depth = len(self._q)
            self._cv.notify_all()
        self._reg.set_gauge("bcos_ingest_queue_depth", depth)
        return entry.task

    def submit(self, tx: Transaction, timeout: float = 30.0
               ) -> TxSubmitResult:
        """Blocking single-tx submission through the batching lane."""
        return self.submit_async(tx).result(timeout)

    def submit_wire_async(self, raw: bytes) -> Task:
        """Enqueue one RAW wire frame; -> Task[TxSubmitResult].

        The columnar front door (ROADMAP item 1): the frame is never
        decoded into a `Transaction` — the dispatcher folds all queued
        wire entries into one `protocol.columnar.decode_columns` +
        `TxPool.submit_columns` call, so per-tx Python marshalling
        disappears from the hot path. Raises TxPoolIsFull at capacity."""
        entry = _Entry(None, Task(), ctx=otrace.current(), wire=raw)
        with self._cv:
            if self._stop:
                raise LaneStopped("ingest lane stopped")
            if len(self._q) >= self.queue_cap:
                self._rejected_total += 1
                self._reg.inc("bcos_ingest_rejected_total")
                raise TxPoolIsFull(
                    f"ingest queue at capacity ({self.queue_cap})")
            self._q.append(entry)
            depth = len(self._q)
            self._cv.notify_all()
        self._reg.set_gauge("bcos_ingest_queue_depth", depth)
        return entry.task

    def submit_wire(self, raw: bytes, timeout: float = 30.0
                    ) -> TxSubmitResult:
        """Blocking single-frame submission through the columnar lane."""
        return self.submit_wire_async(raw).result(timeout)

    def submit_many_wire_nowait(self, wires: Sequence[bytes]) -> int:
        """Fire-and-forget bulk enqueue of RAW wire frames (the gossip
        decode path): same drop-don't-block contract as
        submit_many_nowait, but frames ride to admission undecoded."""
        if not wires:
            return 0
        accepted = 0
        with self._cv:
            if self._stop:
                return 0
            room = self.queue_cap - len(self._q)
            for w in wires[:max(0, room)]:
                self._q.append(_Entry(None, None, wire=w))
                accepted += 1
            depth = len(self._q)
            dropped = len(wires) - accepted
            self._dropped_total += dropped
            if accepted:
                self._cv.notify_all()
        if dropped:
            self._reg.inc("bcos_ingest_dropped_total", dropped)
            metric("ingest.drop", n=dropped)
        self._reg.set_gauge("bcos_ingest_queue_depth", depth)
        return accepted

    def submit_many_nowait(self, txs: Sequence[Transaction]) -> int:
        """Fire-and-forget bulk enqueue (gossip ingestion): accepts what
        fits under the cap and DROPS the rest (-> count accepted). Gossip
        may drop under overload — the pool anti-entropy sweep re-delivers;
        blocking the p2p reader thread on a full queue would back the
        network plane up behind the verify engine instead."""
        if not txs:
            return 0
        accepted = 0
        with self._cv:
            if self._stop:
                return 0
            room = self.queue_cap - len(self._q)
            for tx in txs[:max(0, room)]:
                self._q.append(_Entry(tx, None,
                                      ctx=getattr(tx, "_otrace", None)))
                accepted += 1
            depth = len(self._q)
            dropped = len(txs) - accepted
            self._dropped_total += dropped
            if accepted:
                self._cv.notify_all()
        if dropped:
            self._reg.inc("bcos_ingest_dropped_total", dropped)
            metric("ingest.drop", n=dropped)
        self._reg.set_gauge("bcos_ingest_queue_depth", depth)
        return accepted

    # -- adaptive coalescing -----------------------------------------------
    def _plan(self, queued: int) -> tuple[int, float]:
        """-> (target_batch, window_seconds) for this cycle.

        Idle (low arrival rate AND recent batches of ~1): dispatch
        immediately — a lone RPC tx must not pay a coalescing tax. Under
        load (either signal): target the smallest padding bucket covering
        what's queued plus the load estimate (capped at max_batch) so the
        drained batch fills the executable it will be padded to, and open
        a window toward max_wait. The dispatcher additionally early-exits
        the window when arrivals quiesce (see _run), so the window is an
        upper bound, not a tax."""
        if queued >= self.max_batch:
            return self.max_batch, 0.0
        # busyness is judged over a FIXED horizon, not max_wait: with a
        # small window the gate `rate * max_wait >= 2` could never open
        # (closed-loop submitters post ~1 tx per round trip, so the rate
        # only rises AFTER coalescing starts — a catch-22)
        expected = self._rate * max(self.max_wait, 0.1)
        if expected < 2.0 and self._batch_ewma < 1.5:
            return max(1, queued), 0.0
        want = min(self.max_batch,
                   max(queued, int(self._rate * self.max_wait),
                       int(self._batch_ewma * 2)))
        target = self.max_batch
        for b in _SIZE_BUCKETS:
            if want <= b:
                target = min(b, self.max_batch)
                break
        if queued >= target:
            return target, 0.0
        return target, self.max_wait

    # -- dispatcher --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if not self._q and self._stop:
                    return
                target, window = self._plan(len(self._q))
                if window > 0.0:
                    # park up to `window` for the target, but early-exit
                    # once arrivals quiesce: concurrent submitters re-post
                    # within a few ms of each other after their previous
                    # dispatch resolves, so a short silence means the
                    # in-flight cohort has fully landed. The quiesce
                    # threshold is ADAPTIVE: while the queue is still below
                    # the steady cohort size (the batch EWMA), wait the
                    # patient window/4 — trickling open-loop arrivals keep
                    # coalescing; once a full cohort is in, a ~2 ms silence
                    # suffices. Closed-loop clients' end-to-end rate is
                    # 1/admission-latency, so the old fixed window/4 idle
                    # AFTER the cohort arrived was a direct TPS ceiling.
                    deadline = time.monotonic() + window
                    cohort = max(2.0, self._batch_ewma)
                    gappy = window / 4.0
                    if self._gap_ewma > 0.0:
                        gappy = min(gappy, max(0.005, 8.0 * self._gap_ewma))
                    while (len(self._q) < target and not self._stop):
                        left = deadline - time.monotonic()
                        if left <= 0.0:
                            break
                        before = len(self._q)
                        quiet = 0.002 if before >= cohort else gappy
                        self._cv.wait(min(left, quiet))
                        if len(self._q) == before:
                            break  # quiesced: the cohort is in
                batch = [self._q.popleft()
                         for _ in range(min(len(self._q), self.max_batch))]
                depth = len(self._q)
            self._reg.set_gauge("bcos_ingest_queue_depth", depth)
            try:
                self._dispatch(batch)
            except Exception as exc:  # noqa: BLE001 — lane must survive
                LOG.exception(badge("INGEST", "dispatch-failed",
                                    n=len(batch)))
                for e in batch:
                    if e.task is not None:
                        e.task.reject(exc)

    def _dispatch(self, batch: list[_Entry]) -> None:
        now = time.monotonic()
        # columnar entries (raw wire frames) and object entries dispatch
        # through their own pool doors; a mixed drain pays two recover
        # calls, but producers are homogeneous per deployment (wire RPC +
        # wire gossip, or legacy object submitters), so the mix is a
        # transition artifact, not the steady state
        wire_entries = [e for e in batch if e.tx is None]
        obj_entries = [e for e in batch if e.tx is not None]
        # deadline shed BEFORE any admission/crypto work: entries whose
        # block_limit already passed while they sat in the queue can never
        # commit — settle them with the typed expiry status instead of
        # spending lane verify + pool slots on work that would be dropped
        # anyway (they would be rejected by the pool's precheck, but under
        # overload even carrying them through the batch costs real time).
        # Wire entries skip this: reading block_limit would mean decoding,
        # and submit_columns' precheck rejects expired rows BEFORE the
        # recover anyway (they pay one batched hash slot, nothing more).
        ledger = getattr(self.txpool, "ledger", None)  # test doubles may
        current = ledger.current_number() if ledger is not None else None
        shed = [e for e in obj_entries
                if current is not None and e.tx.block_limit <= current]
        if shed:
            from ..protocol import TransactionStatus, batch_hash
            hs = batch_hash([e.tx for e in shed], self.txpool.suite)
            for e, h in zip(shed, hs):
                if e.task is not None:
                    e.task.resolve(TxSubmitResult(
                        h, TransactionStatus.BLOCK_LIMIT_CHECK_FAIL))
            self._reg.inc("bcos_ingest_deadline_shed_total", len(shed))
            obj_entries = [e for e in obj_entries
                           if e.tx.block_limit > current]
            batch = obj_entries + wire_entries
            if not batch:
                return
        # one pool call per path == one device recover for the drained set
        from ..analysis.profiler import stage as _prof_stage
        t0 = time.perf_counter()
        with _prof_stage("ingest.admit"):
            if obj_entries:
                results = self.txpool.submit_batch(
                    [e.tx for e in obj_entries], broadcast=self.broadcast)
                for e, res in zip(obj_entries, results):
                    if e.task is not None:
                        e.task.resolve(res)
            if wire_entries:
                from ..protocol.columnar import decode_columns
                cols = decode_columns([e.wire for e in wire_entries])
                results = self.txpool.submit_columns(
                    cols, broadcast=self.broadcast)
                for e, res in zip(wire_entries, results):
                    if e.task is not None:
                        e.task.resolve(res)
        dt = time.perf_counter() - t0
        # latency attribution: per-batch coalesce time into the stage
        # histogram; traced submissions additionally get their own
        # enqueue-to-admitted span (one per traced entry, linked to the
        # shared batch by the batch-size attribute)
        # unlabeled registry on purpose: every bcos_tx_stage_seconds
        # stage must live in ONE series family or cross-stage shares
        # (the dashboard's headline panel) skew — the block stages are
        # unlabeled, so these are too
        observe_stage("ingest", now - batch[0].t_enq)
        t_done = time.monotonic()
        for e in batch:
            if e.ctx is not None and e.ctx.sampled:
                otrace.TRACER.record(
                    "ingest.admit", e.ctx, e.t_enq, t_done,
                    attrs={"batch": len(batch),
                           "node": self.trace_label})
        # rate EWMA: arrivals per second over the inter-dispatch gap
        gap = max(1e-6, now - self._last_dispatch)
        self._last_dispatch = now
        inst = len(batch) / gap
        self._rate = inst if self._rate == 0.0 else \
            0.3 * inst + 0.7 * self._rate
        self._batch_ewma = 0.3 * len(batch) + 0.7 * self._batch_ewma
        if len(batch) > 1:
            spread = (batch[-1].t_enq - batch[0].t_enq) / (len(batch) - 1)
            self._gap_ewma = spread if self._gap_ewma == 0.0 else \
                0.3 * spread + 0.7 * self._gap_ewma
        with self._cv:
            self._txs_total += len(batch)
            self._batches_total += 1
        self._reg.inc("bcos_ingest_txs_total", len(batch))
        self._reg.inc("bcos_ingest_batches_total")
        self._reg.observe("bcos_ingest_batch_size", len(batch),
                         buckets=_SIZE_BUCKETS)
        self._reg.observe("bcos_ingest_coalesce_delay_seconds",
                         now - batch[0].t_enq)
        self._reg.observe("bcos_ingest_per_tx_seconds", dt / len(batch))
        metric("ingest.batch", n=len(batch), ms=int(dt * 1000),
               rate=int(self._rate))

    # -- introspection -----------------------------------------------------
    def queue_fraction(self) -> float:
        """Queue occupancy 0..1 — the overload controller's ingest signal
        (utils/overload.py). Lock-free read of a len()."""
        return len(self._q) / max(1, self.queue_cap)

    def stats(self) -> dict:
        with self._cv:
            txs, batches = self._txs_total, self._batches_total
            return {
                "txs_total": txs,
                "batches_total": batches,
                "mean_batch": round(txs / batches, 2) if batches else 0.0,
                "queue_depth": len(self._q),
                "rejected_total": self._rejected_total,
                "dropped_total": self._dropped_total,
                "rate_tx_per_sec": round(self._rate, 1),
            }
