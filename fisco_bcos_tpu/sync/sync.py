"""BlockSync — download/broadcast state machine for lagging nodes.

Reference counterpart: /root/reference/bcos-sync/bcos-sync/BlockSync.cpp
(:183 executeWorker -> :194 maintainPeersStatus, :200
maintainDownloadingQueue, :216 maintainBlockRequest) — peers gossip their
latest number, a lagging node requests ranges, and every fetched block's
commit seals are batch-verified before replay
(bcos-pbft/bcos-pbft/pbft/engine/BlockValidator.cpp:141 checkSignatureList —
here ONE `suite.verify_batch` call across all seals of all fetched blocks).

Wire payloads (module BlockSync):
  push:     status  = i64 number | blob latest_hash | i64 utc_ms
            (utc_ms feeds NodeTimeMaintenance, tool/timesync.py — the
            reference's NodeTimeMaintenance.cpp rides the same gossip)
  request:  range   = i64 from | i64 to
  response: blocks  = seq<blob block-encoding (full txs)>
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..codec.wire import Reader, Writer
from ..net.front import FrontService
from ..net.moduleid import ModuleID
from ..protocol import Block, BlockHeader
from ..utils.log import LOG, badge, metric
from ..utils.worker import Worker

MAX_BLOCKS_PER_REQUEST = 32


class BlockSync(Worker):
    def __init__(self, front: FrontService, ledger, scheduler, suite,
                 status_interval: float = 1.0, timesync=None):
        super().__init__("block-sync", idle_wait=0.1)
        self.front = front
        self.ledger = ledger
        self.scheduler = scheduler
        self.suite = suite
        self.timesync = timesync  # tool.timesync.NodeTimeMaintenance
        self.status_interval = status_interval
        # peer -> (latest number, monotonic last-seen); silent peers are
        # pruned so a departed node can't pin the download target or the
        # timesync median forever
        self._peers: dict[bytes, tuple[int, float]] = {}
        self._lock = threading.Lock()
        self._last_status = 0.0
        self._inflight = False
        front.register_module(ModuleID.BlockSync, self._on_message)

    # -- worker ------------------------------------------------------------
    PEER_TTL_INTERVALS = 10  # silent for 10 status periods -> forgotten

    def execute_worker(self) -> None:
        now = time.monotonic()
        if now - self._last_status >= self.status_interval:
            self._last_status = now
            self.broadcast_status()
            self._prune_peers(now)
        self._maybe_download()

    def _prune_peers(self, now: float) -> None:
        ttl = self.status_interval * self.PEER_TTL_INTERVALS
        with self._lock:
            dead = [p for p, (_, seen) in self._peers.items()
                    if now - seen > ttl]
            for p in dead:
                del self._peers[p]
        for p in dead:
            if self.timesync is not None:
                self.timesync.forget_peer(p)

    def broadcast_status(self) -> None:
        n = self.ledger.current_number()
        h = self.ledger.header_by_number(n)
        payload = (Writer().i64(n)
                   .blob(h.hash(self.suite) if h else b"")
                   .i64(int(time.time() * 1000)).bytes())
        self.front.broadcast(ModuleID.BlockSync, payload)

    def _maybe_download(self) -> None:
        if self._inflight:
            return
        current = self.ledger.current_number()
        with self._lock:
            ahead = [(p, n) for p, (n, _) in self._peers.items()
                     if n > current]
        if not ahead:
            return
        peer, peer_number = max(ahead, key=lambda x: x[1])
        lo = current + 1
        hi = min(peer_number, current + MAX_BLOCKS_PER_REQUEST)
        self._inflight = True
        try:
            req = Writer().i64(lo).i64(hi).bytes()
            resp = self.front.request(ModuleID.BlockSync, peer, req,
                                      timeout=10.0)
            if resp is None:
                return
            blocks = Reader(resp).seq(lambda r: Block.decode(r.blob()))
            self._apply_blocks(blocks)
        finally:
            self._inflight = False
            self.wakeup()

    # -- verification + replay --------------------------------------------
    def _verify_seals(self, header: BlockHeader) -> bool:
        """Verify one block's commit seals against the LOCAL ledger's sealer
        set (never the peer-supplied header.sealer_list — a malicious peer
        could fabricate that), deduplicated by sealer index, quorum 2f+1.
        All seals go through one batch verify (BlockValidator.cpp:141)."""
        sealer_set = sorted(n.node_id for n in self.ledger.consensus_nodes()
                            if n.node_type == "consensus_sealer")
        if list(header.sealer_list) != sealer_set:
            LOG.warning(badge("SYNC", "sealer-list-mismatch",
                              number=header.number))
            return False
        hh = header.hash(self.suite)
        by_idx: dict[int, bytes] = {}
        for idx, seal in header.signature_list:
            if 0 <= idx < len(sealer_set):
                by_idx.setdefault(idx, seal)
        n = len(sealer_set)
        quorum = 2 * ((n - 1) // 3) + 1
        if len(by_idx) < quorum:
            return False
        idxs = sorted(by_idx)
        ok = np.asarray(self.suite.verify_batch(
            [hh] * len(idxs), [by_idx[i] for i in idxs],
            [sealer_set[i] for i in idxs]))
        if int(ok.sum()) < quorum:
            LOG.warning(badge("SYNC", "seal-quorum-failed",
                              number=header.number))
            return False
        return True

    def _apply_blocks(self, blocks: list[Block]) -> None:
        blocks = [b for b in blocks
                  if b.header.number > self.ledger.current_number()]
        blocks.sort(key=lambda b: b.header.number)
        for block in blocks:
            # verify per block, AFTER the previous replay: the sealer set is
            # ledger state and may change at any height
            if not self._verify_seals(block.header):
                return
            synced = block.header
            expect_hash = synced.hash(self.suite)
            replay = Block(transactions=block.transactions)
            replay.header.version = synced.version
            replay.header.consensus_weights = list(synced.consensus_weights)
            replay.header.number = synced.number
            replay.header.timestamp = synced.timestamp
            replay.header.sealer = synced.sealer
            replay.header.sealer_list = list(synced.sealer_list)
            replay.header.extra_data = synced.extra_data
            result = self.scheduler.execute_block(replay)
            if result is None:
                return
            if result.header.hash(self.suite) != expect_hash:
                LOG.error(badge("SYNC", "replay-hash-mismatch",
                                number=synced.number))
                self.scheduler.drop_executed(result.header)
                return
            result.header.signature_list = synced.signature_list
            if not self.scheduler.commit_block(result.header):
                return
            metric("sync.committed", number=synced.number)

    # -- serving + status ingest ------------------------------------------
    def _on_message(self, src: bytes, payload: bytes, respond) -> None:
        if respond is not None:  # range request: serve blocks
            r = Reader(payload)
            lo, hi = r.i64(), r.i64()
            hi = min(hi, lo + MAX_BLOCKS_PER_REQUEST - 1,
                     self.ledger.current_number())
            out = []
            for n in range(lo, hi + 1):
                b = self.ledger.block_by_number(n, with_txs=True)
                if b is None:
                    break
                out.append(b)
            respond(Writer().seq(out, lambda w, b: w.blob(b.encode())).bytes())
            return
        r = Reader(payload)
        number = r.i64()
        if self.timesync is not None:
            try:
                r.blob()  # latest_hash
                self.timesync.update_peer_time(src, r.i64())
            except Exception:
                pass  # pre-timesync peers: status without a clock field
        with self._lock:
            self._peers[src] = (number, time.monotonic())
        if number > self.ledger.current_number():
            self.wakeup()

    def status(self) -> dict:
        with self._lock:
            peers = {p.hex()[:16]: n for p, (n, _) in self._peers.items()}
        return {"blockNumber": self.ledger.current_number(),
                "peers": peers}
