"""BlockSync — download/broadcast state machine for lagging nodes.

Reference counterpart: /root/reference/bcos-sync/bcos-sync/BlockSync.cpp
(:183 executeWorker -> :194 maintainPeersStatus, :200
maintainDownloadingQueue, :216 maintainBlockRequest) — peers gossip their
latest number, a lagging node requests ranges, and every fetched block's
commit seals are batch-verified before replay
(bcos-pbft/bcos-pbft/pbft/engine/BlockValidator.cpp:141 checkSignatureList —
here ONE `suite.verify_batch` call across all seals of all fetched blocks).

Two worker threads, deliberately: the STATUS worker broadcasts our height
and prunes silent peers on a fixed cadence; the DOWNLOAD worker issues the
blocking range/snapshot requests. A slow or dead peer can therefore stall a
download for its full timeout without ever delaying our own status gossip —
previously both ran on one loop and a 10 s request starved
`broadcast_status` long enough for peers to TTL-prune us.

Sync modes:
  * replay — fetch block ranges, verify seals, re-execute, commit (the
    default catch-up path);
  * snap   — when a peer is more than `snap_sync_threshold` blocks ahead
    (or answers "pruned-below" for a requested range), fetch its snapshot
    manifest + chunks over ModuleID.SnapshotSync, batch-verify chunk hashes
    against the manifest root and the checkpoint header's commit seals
    (the same `_verify_seals`), install the state, then replay only the
    tail. O(state size) batched hashing instead of O(chain length) replay.

Wire payloads (module BlockSync):
  push:     status  = i64 number | blob latest_hash | i64 utc_ms
            (utc_ms feeds NodeTimeMaintenance, tool/timesync.py — the
            reference's NodeTimeMaintenance.cpp rides the same gossip)
  request:  range   = i64 from | i64 to
  response: u8 flag — RESP_BLOCKS: seq<blob block-encoding (full txs)>,
                      byte-capped: the server returns fewer blocks when
                      MAX_RESPONSE_BYTES is hit and the client re-requests;
            RESP_PRUNED: i64 pruned_below — the server pruned bodies below
                      that height; the client fails over to snap-sync.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..codec.wire import Reader, Writer
from ..consensus import qc
from ..net.front import FrontService
from ..net.moduleid import ModuleID
from ..protocol import Block, BlockHeader
from ..utils.log import LOG, badge, metric
from ..utils.metrics import REGISTRY
from ..utils.worker import Worker

MAX_BLOCKS_PER_REQUEST = 32
# full-tx blocks are unbounded; a 32-block response must still fit a gossip
# frame, so the server stops adding blocks at this budget and the client
# simply re-requests from where the response ended
MAX_RESPONSE_BYTES = 1 << 20
# must stay well below status_interval * PEER_TTL_INTERVALS: a request's
# worst-case block time on the download worker must never approach the TTL
# that peers apply to OUR silence
REQUEST_TIMEOUT = 5.0

RESP_BLOCKS = 0
RESP_PRUNED = 1

SNAP_RETRY_SECONDS = 5.0  # failed snap attempt: back off, replay continues


class _DownloadWorker(Worker):
    """Dedicated thread for the blocking download requests."""

    def __init__(self, sync: "BlockSync"):
        super().__init__("block-sync-dl", idle_wait=0.1)
        self._sync = sync

    def execute_worker(self) -> None:
        self._sync._maybe_download()


class BlockSync(Worker):
    def __init__(self, front: FrontService, ledger, scheduler, suite,
                 status_interval: float = 1.0, timesync=None,
                 snapshot=None, snap_sync_threshold: int = 0,
                 registry=None, agg_registry=None):
        super().__init__("block-sync", idle_wait=0.1)
        # metrics sink: multi-group nodes pass a group-labeled view
        self._reg = registry if registry is not None else REGISTRY
        # PoP'd BLS key roster (crypto/agg.py) — needed only to accept
        # aggregate-mode certificates; without it those blocks are rejected
        self.agg_registry = agg_registry
        self.front = front
        self.ledger = ledger
        self.scheduler = scheduler
        self.suite = suite
        self.timesync = timesync  # tool.timesync.NodeTimeMaintenance
        self.status_interval = status_interval
        self.snapshot = snapshot  # snapshot.service.SnapshotService | None
        # 0 disables snap-sync preference (pruned-below answers still
        # trigger it — replay is impossible there)
        self.snap_sync_threshold = snap_sync_threshold
        self.sync_mode = "replay"  # last catch-up mechanism used
        # peer -> (latest number, monotonic last-seen); silent peers are
        # pruned so a departed node can't pin the download target or the
        # timesync median forever
        self._peers: dict[bytes, tuple[int, float]] = {}
        # peer -> its advertised prune floor: a range request below it is a
        # guaranteed RESP_PRUNED round trip, so the download worker goes
        # straight to the (backed-off) snap path instead of re-asking every
        # idle tick
        self._pruned_floors: dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._last_status = 0.0
        self._inflight = False
        self._next_snap_attempt = 0.0
        self._downloader = _DownloadWorker(self)
        self._reg.set_gauge("bcos_sync_mode", 0)  # 0 replay | 1 snap
        front.register_module(ModuleID.BlockSync, self._on_message)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        super().start()
        self._downloader.start()

    def stop(self) -> None:
        self._downloader.stop()
        super().stop()

    # -- status worker (gossip cadence; never blocks on a peer) -----------
    PEER_TTL_INTERVALS = 10  # silent for 10 status periods -> forgotten

    def execute_worker(self) -> None:
        now = time.monotonic()
        if now - self._last_status >= self.status_interval:
            self._last_status = now
            self.broadcast_status()
            self._prune_peers(now)

    def _prune_peers(self, now: float) -> None:
        ttl = self.status_interval * self.PEER_TTL_INTERVALS
        with self._lock:
            dead = [p for p, (_, seen) in self._peers.items()
                    if now - seen > ttl]
            for p in dead:
                del self._peers[p]
                self._pruned_floors.pop(p, None)
        for p in dead:
            if self.timesync is not None:
                self.timesync.forget_peer(p)

    def broadcast_status(self) -> None:
        n = self.ledger.current_number()
        h = self.ledger.header_by_number(n)
        payload = (Writer().i64(n)
                   .blob(h.hash(self.suite) if h else b"")
                   .i64(int(time.time() * 1000)).bytes())
        self.front.broadcast(ModuleID.BlockSync, payload)

    # -- download worker ---------------------------------------------------
    def _maybe_download(self) -> None:
        if self._inflight:
            return
        current = self.ledger.current_number()
        with self._lock:
            ahead = [(p, n) for p, (n, _) in self._peers.items()
                     if n > current]
            floors = dict(self._pruned_floors)
        if not ahead:
            return
        peer, peer_number = max(ahead, key=lambda x: x[1])
        self._inflight = True
        try:
            if (self.snap_sync_threshold > 0
                    and peer_number - current > self.snap_sync_threshold):
                if self._try_snap_sync(peer):
                    return
                if self._downloader.stopping():
                    # the attempt may have aborted because stop() was
                    # requested — don't fall through and start a range
                    # download during shutdown
                    return
            lo = current + 1
            if lo < floors.get(peer, 0):
                # the peer already told us it pruned this range; its
                # snapshot (behind the snap-attempt backoff) is the only
                # way forward — don't re-send the doomed range request
                self._try_snap_sync(peer)
                return
            hi = min(peer_number, current + MAX_BLOCKS_PER_REQUEST)
            req = Writer().i64(lo).i64(hi).bytes()
            resp = self.front.request(ModuleID.BlockSync, peer, req,
                                      timeout=REQUEST_TIMEOUT)
            if resp is None:
                return
            r = Reader(resp)
            flag = r.u8()
            if flag == RESP_PRUNED:
                floor = r.i64()
                with self._lock:
                    self._pruned_floors[peer] = floor
                LOG.info(badge("SYNC", "peer-pruned-below", floor=floor,
                               requested=lo))
                # replay below the peer's floor is impossible: the ONLY way
                # forward is its snapshot
                self._try_snap_sync(peer)
                return
            blocks = r.seq(lambda rr: Block.decode(rr.blob()))
            self._apply_blocks(blocks)
        finally:
            self._inflight = False
            self.wakeup()

    def _try_snap_sync(self, peer: bytes) -> bool:
        now = time.monotonic()
        if now < self._next_snap_attempt:
            return False
        from ..snapshot.importer import snap_sync
        t0 = time.monotonic()
        # flip the mode BEFORE snap_sync: the install's storage commit
        # publishes the new height, and an observer gating on
        # current_number (chain_bench run_sync_bench) must never read the
        # stale "replay" mode after seeing the post-install height
        prev_mode = self.sync_mode
        self.sync_mode = "snap"
        self._reg.set_gauge("bcos_sync_mode", 1)
        res = snap_sync(self.front, peer, self.ledger.storage, self.suite,
                        self._verify_seals, self.ledger.current_number(),
                        request_timeout=REQUEST_TIMEOUT,
                        should_abort=self._downloader.stopping,
                        pre_install=None if self.scheduler is None else
                        lambda: self.scheduler.invalidate_caches(
                            self.ledger.current_number()),
                        registry=self._reg)
        if res is None:
            self.sync_mode = prev_mode
            self._reg.set_gauge("bcos_sync_mode",
                               1 if prev_mode == "snap" else 0)
            self._next_snap_attempt = now + SNAP_RETRY_SECONDS
            return False
        manifest, chunks = res
        if self.snapshot is not None:
            # become a server for the next joiner (pruned peers included)
            self.snapshot.adopt(manifest, chunks)
        if self.scheduler is not None:
            self.scheduler.external_commit(manifest.height)
        LOG.info(badge("SYNC", "snap-sync-installed", number=manifest.height,
                       chunks=manifest.chunk_count,
                       secs=round(time.monotonic() - t0, 2)))
        metric("sync.snap_installed", number=manifest.height)
        return True

    # -- verification + replay --------------------------------------------
    def _verify_seals(self, header: BlockHeader) -> bool:
        """Verify one block's commit-seal carriage — legacy 2f+1 multi-seal
        OR a quorum certificate (consensus/qc.py), both judged against the
        LOCAL ledger's sealer set (never the peer-supplied
        header.sealer_list — a malicious peer could fabricate that).
        Admission rules are shared with the range-wide batched pre-pass
        because both are the same `qc.verify_spans` call."""
        if not qc.verify_spans([header], self._sealer_set(), self.suite,
                               agg_registry=self.agg_registry)[0]:
            LOG.warning(badge("SYNC", "seal-quorum-failed",
                              number=header.number))
            return False
        return True

    def _sealer_set(self) -> list[bytes]:
        return sorted(n.node_id for n in self.ledger.consensus_nodes()
                      if n.node_type == "consensus_sealer")

    @staticmethod
    def _collect_seals(header: BlockHeader, sealer_set: list[bytes]
                       ) -> Optional[tuple[list[int], list[bytes]]]:
        """Legacy multi-seal admission (kept for callers/tests that judge
        structure without crypto) — now a thin wrapper over the shared
        rule set in consensus/qc.py."""
        quorum = 2 * ((len(sealer_set) - 1) // 3) + 1
        return qc.collect_legacy(header, sealer_set, quorum,
                                 check_sealer_list=True)

    def _batch_verify_seals(self, headers: list[BlockHeader]
                            ) -> tuple[dict[bytes, bool], list[bytes]]:
        """ONE `suite.verify_batch` across every header's commit seals (the
        PBFT drain-loop trick, engine._batch_checked) instead of a device
        round trip per block — a range response may mix legacy multi-seal
        blocks and certificate blocks (a chain that lived through a
        seal_mode rollout) and `qc.verify_spans` merges both forms into
        the same lane call. Returns ({header hash: quorum-ok}, the
        sealer set the batch was judged against). Verdicts are keyed by
        HEADER HASH, never height: a response may carry two different
        blocks at one height, and a by-number verdict would let a forged
        one ride a legit sibling's True. The replay loop falls back to
        the per-block `_verify_seals` for any header this pre-pass
        rejected or whenever a replayed block changes the on-chain
        sealer set."""
        sealer_set = self._sealer_set()
        ok = qc.verify_spans(headers, sealer_set, self.suite,
                             agg_registry=self.agg_registry)
        out = {h.hash(self.suite): bool(v) for h, v in zip(headers, ok)}
        return out, sealer_set

    def _apply_blocks(self, blocks: list[Block]) -> None:
        blocks = [b for b in blocks
                  if b.header.number > self.ledger.current_number()]
        blocks.sort(key=lambda b: b.header.number)
        if not blocks:
            return
        # replay needs the execution slot at committed+1; consensus may
        # hold a speculative chain built on rounds the cluster moved past
        # (we would not be downloading otherwise) — discard it first
        nxt = getattr(self.scheduler, "next_executable", None)
        abort = getattr(self.scheduler, "abort_speculation", None)
        if nxt is not None and abort is not None \
                and nxt() != self.ledger.current_number() + 1:
            abort()
        # coalesce seal verification for the whole response into one batch
        pre, batch_set = self._batch_verify_seals([b.header for b in blocks])
        for block in blocks:
            if block.header.number <= self.ledger.current_number():
                continue  # duplicate within the response: already committed
            if block.header.number != self.ledger.current_number() + 1:
                return  # gap: stop, the next request refetches from here
            # the sealer set is ledger state and may change at any replayed
            # height: the batched verdict only holds while the set still
            # matches the one the batch was judged against
            if self._sealer_set() == batch_set \
                    and pre.get(block.header.hash(self.suite)) is True:
                pass  # seals verified in the range-wide batch
            elif not self._verify_seals(block.header):
                return
            synced = block.header
            expect_hash = synced.hash(self.suite)
            replay = Block(transactions=block.transactions)
            replay.header.version = synced.version
            replay.header.consensus_weights = list(synced.consensus_weights)
            replay.header.number = synced.number
            replay.header.timestamp = synced.timestamp
            replay.header.sealer = synced.sealer
            replay.header.sealer_list = list(synced.sealer_list)
            replay.header.extra_data = synced.extra_data
            result = self.scheduler.execute_block(replay)
            if result is None:
                return
            if result.header.hash(self.suite) != expect_hash:
                LOG.error(badge("SYNC", "replay-hash-mismatch",
                                number=synced.number))
                self.scheduler.drop_executed(result.header)
                return
            result.header.signature_list = synced.signature_list
            if not self.scheduler.commit_block(result.header):
                return
            metric("sync.committed", number=synced.number)

    # -- serving + status ingest ------------------------------------------
    def _on_message(self, src: bytes, payload: bytes, respond) -> None:
        if respond is not None:  # range request: serve blocks
            r = Reader(payload)
            lo, hi = r.i64(), r.i64()
            floor = self.ledger.pruned_below()
            if lo < floor:
                # bodies below the floor are gone — answering with an empty
                # block list would leave the downloader retrying forever;
                # tell it to fail over to snap-sync instead
                respond(Writer().u8(RESP_PRUNED).i64(floor).bytes())
                return
            hi = min(hi, lo + MAX_BLOCKS_PER_REQUEST - 1,
                     self.ledger.current_number())
            out = []
            budget = MAX_RESPONSE_BYTES
            for n in range(lo, hi + 1):
                b = self.ledger.block_by_number(n, with_txs=True)
                if b is None:
                    break
                enc = b.encode()
                if out and len(enc) > budget:
                    break  # byte cap: client re-requests the rest
                budget -= len(enc)
                out.append(enc)
            w = Writer().u8(RESP_BLOCKS)
            respond(w.seq(out, lambda ww, e: ww.blob(e)).bytes())
            return
        r = Reader(payload)
        number = r.i64()
        if self.timesync is not None:
            try:
                r.blob()  # latest_hash
                self.timesync.update_peer_time(src, r.i64())
            except Exception:
                pass  # pre-timesync peers: status without a clock field
        with self._lock:
            self._peers[src] = (number, time.monotonic())
        if number > self.ledger.current_number():
            self._downloader.wakeup()

    def wakeup(self) -> None:  # downloads react to status pushes/completions
        super().wakeup()
        self._downloader.wakeup()

    def status(self) -> dict:
        with self._lock:
            peers = {p.hex()[:16]: n for p, (n, _) in self._peers.items()}
        return {"blockNumber": self.ledger.current_number(),
                "peers": peers,
                "syncMode": self.sync_mode,
                "prunedBelow": self.ledger.pruned_below()}
