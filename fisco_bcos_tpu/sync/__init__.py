from .sync import BlockSync

__all__ = ["BlockSync"]
