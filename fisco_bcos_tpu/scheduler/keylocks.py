"""GraphKeyLocks — cross-contract key locking with deadlock detection.

Reference counterpart: /root/reference/bcos-scheduler/src/GraphKeyLocks.cpp
(+ test/testKeyLocks.cpp semantics): DMC execution shards transactions by
contract; when a transaction's call chain crosses into another contract it
must hold that contract's key locks, and a cycle in the wait-for graph means
deadlock — the scheduler reverts one participant and re-runs it in a later
round (BlockExecutive.cpp:861 DMCExecute loop).

Locks are (contract, key) -> holder tx. A tx may hold many keys (re-entrant
per tx). `acquire` either grants, or registers a wait edge and reports
whether waiting would close a cycle (deadlock): the *requesting* tx is then
the designated victim, matching the reference's revert-the-requester
strategy.
"""

from __future__ import annotations

import threading
from typing import Hashable, Optional

LockId = tuple[bytes, bytes]  # (contract, key)


class DeadlockError(Exception):
    def __init__(self, tx: Hashable, cycle: list[Hashable]):
        super().__init__(f"deadlock: tx {tx!r} in cycle {cycle!r}")
        self.tx = tx
        self.cycle = cycle


class GraphKeyLocks:
    def __init__(self):
        self._holders: dict[LockId, Hashable] = {}
        self._held: dict[Hashable, set[LockId]] = {}
        self._waiting: dict[Hashable, LockId] = {}  # tx -> lock it waits on
        self._cv = threading.Condition()

    # -- wait-for graph ----------------------------------------------------
    def _would_deadlock(self, tx: Hashable, lock: LockId) -> Optional[list]:
        """Follow holder->waiting edges from `lock`; a path back to tx is a
        cycle."""
        path = [tx]
        cur = self._holders.get(lock)
        while cur is not None:
            if cur == tx:
                return path
            path.append(cur)
            nxt = self._waiting.get(cur)
            if nxt is None:
                return None
            cur = self._holders.get(nxt)
        return None

    # -- public API --------------------------------------------------------
    def try_acquire(self, tx: Hashable, contract: bytes, key: bytes) -> bool:
        """Non-blocking: grant if free or already ours; False if held."""
        lock = (contract, key)
        with self._cv:
            holder = self._holders.get(lock)
            if holder is None or holder == tx:
                self._holders[lock] = tx
                self._held.setdefault(tx, set()).add(lock)
                return True
            return False

    def acquire(self, tx: Hashable, contract: bytes, key: bytes,
                timeout: float = 5.0) -> None:
        """Blocking acquire; raises DeadlockError if waiting closes a cycle
        (the caller must revert tx and release its locks)."""
        lock = (contract, key)
        with self._cv:
            while True:
                holder = self._holders.get(lock)
                if holder is None or holder == tx:
                    self._holders[lock] = tx
                    self._held.setdefault(tx, set()).add(lock)
                    self._waiting.pop(tx, None)
                    return
                cycle = self._would_deadlock(tx, lock)
                if cycle is not None:
                    self._waiting.pop(tx, None)
                    raise DeadlockError(tx, cycle)
                self._waiting[tx] = lock
                if not self._cv.wait(timeout):
                    self._waiting.pop(tx, None)
                    raise TimeoutError(f"key lock wait timed out: {lock!r}")

    def release_all(self, tx: Hashable) -> None:
        with self._cv:
            for lock in self._held.pop(tx, set()):
                if self._holders.get(lock) == tx:
                    del self._holders[lock]
            self._waiting.pop(tx, None)
            self._cv.notify_all()

    def holder_of(self, contract: bytes, key: bytes) -> Optional[Hashable]:
        with self._cv:
            return self._holders.get((contract, key))

    def held_by(self, tx: Hashable) -> set[LockId]:
        with self._cv:
            return set(self._held.get(tx, set()))
