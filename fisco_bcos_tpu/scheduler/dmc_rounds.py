"""DMC message rounds: scheduler <-> sharded-executor iterative protocol.

Reference counterpart: /root/reference/bcos-scheduler/src/BlockExecutive.cpp
:861-978 (DMCExecute loops rounds until every executor reports FINISHED),
DmcExecutor.h:38-80 (per-contract message queues: submit/prepare/go),
CoroutineTransactionExecutive.h (an executive PAUSES at a cross-contract
call and round-trips an ExecutionMessage through the scheduler), and
GraphKeyLocks.cpp (cross-executor lock graph with deadlock revert).

This is the protocol that lets executors scale OUT (Max mode: one executor
process per contract partition) while cross-contract calls still work:

  * each `ShardExecutor` owns a partition of contract addresses and runs
    call frames as thread-bridged executives (the boost::context coroutine
    analogue) over a per-(shard, context) state overlay;
  * an EVM CALL leaving the shard pauses the executive and surfaces a
    CALL message; the scheduler routes it to the owning shard, which runs
    it as a new executive (nested/re-entrant cross-shard chains compose);
    the response resumes the paused frame;
  * a context entering a shard takes the shard's key lock until the whole
    context finishes — opposite acquisition orders across shards deadlock,
    which the scheduler detects (no runnable message + blocked contexts)
    and resolves the reference's way: revert the HIGHEST context id
    (abort its executives, discard its overlays, release its locks) and
    re-run it after the survivors (DmcExecutor's revert-and-retry).

Determinism: messages are processed strictly sequentially in deterministic
order (FIFO of generation, which is itself a pure function of the block),
lock grants and deadlock victims are order-functions of context ids, and a
context's writes merge into the block state only when it finishes — so
every replica derives the same receipts and state root. In-process the
sequential loop costs nothing (the state mutation is lock-serialised
anyway); across processes the same message objects ride the service RPC.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from collections import deque
from typing import Callable, Optional, Sequence

from ..executor.executor import TransactionExecutor
from ..executor.evm import EVMResult
from ..protocol import Receipt, Transaction, TransactionStatus
from ..storage.state import StateStorage
from ..utils.log import LOG, badge, metric
from ..utils.trace import DmcStepRecorder

MSG_ROOT, MSG_CALL = 0, 1


MAX_XSHARD_DEPTH = 64  # cap on cross-shard hops (each costs an executive)


@dataclasses.dataclass
class DmcMessage:
    """One scheduler<->executor message (ExecutionMessage analogue)."""

    kind: int
    context_id: int
    seq: int
    to: bytes  # routed contract address
    caller: bytes = b""
    value: int = 0
    data: bytes = b""
    gas: int = 0
    static: bool = False
    depth: int = 0  # EVM call depth carried ACROSS shards
    tx: Optional[Transaction] = None  # MSG_ROOT only


class _Aborted(Exception):
    """Raised inside an executive thread when its context is reverted."""


class _Executive:
    """A call frame on its own thread; pauses at cross-shard calls.

    The thread runs `fn(external)` where `external(msg) -> response` blocks
    until the scheduler routes the call and feeds the answer back — the
    shape of CoroutineTransactionExecutive's yield/resume.
    """

    def __init__(self, fn: Callable):
        self._outbox: "queue.Queue[tuple[str, object]]" = queue.Queue()
        self._inbox: "queue.Queue[tuple[str, object]]" = queue.Queue()
        self._thread = threading.Thread(target=self._main, args=(fn,),
                                        name="dmc-executive", daemon=True)

    def _main(self, fn) -> None:
        try:
            result = fn(self._external)
            self._outbox.put(("done", result))
        except _Aborted:
            self._outbox.put(("aborted", None))
        except Exception as exc:  # defensive; surfaces as a failed receipt
            self._outbox.put(("error", exc))

    def _external(self, request):
        self._outbox.put(("call", request))
        kind, resp = self._inbox.get()
        if kind == "abort":
            raise _Aborted()
        return resp

    def start(self) -> tuple[str, object]:
        self._thread.start()
        return self._outbox.get()

    def resume(self, response) -> tuple[str, object]:
        self._inbox.put(("resp", response))
        return self._outbox.get()

    def abort(self) -> None:
        """Only valid while paused (which a deadlocked executive is)."""
        self._inbox.put(("abort", None))
        self._outbox.get()  # the ("aborted", None) ack
        self._thread.join(timeout=5)


class ShardExecutor:
    """One contract partition: executor + per-context overlays + executives.

    `owns(addr)` defines the partition; in Max deployments this object sits
    behind the executor-service RPC (services/executor_service.py) — the
    scheduler only ever exchanges DmcMessages with it.
    """

    def __init__(self, shard_id: bytes, suite,
                 owns: Callable[[bytes], bool],
                 precompile_home: bool = False):
        self.shard_id = shard_id
        self.suite = suite
        self.owns = owns
        # system precompiles live on ONE deterministic shard (the scheduler
        # marks shards[0]) so their state has a single writer under the
        # shard lock — replicating them would lose updates at merge
        self.precompile_home = precompile_home
        self.executor = TransactionExecutor(suite)
        self._tls = threading.local()
        self.executor.evm.external_call = self._hook
        self._overlays: dict[int, StateStorage] = {}

    def _is_local(self, to: bytes) -> bool:
        if to in self.executor.registry:
            return self.precompile_home
        return self.owns(to)

    # -- cross-shard hook (runs ON an executive thread) --------------------
    def _hook(self, caller, to, value, data, gas, static, depth):
        if self._is_local(to):
            return None
        external = getattr(self._tls, "external", None)
        if external is None:
            return None  # not executing under the round scheduler
        if value:
            return EVMResult(False, gas_left=gas,
                             error="cross-shard value transfer unsupported")
        total_depth = getattr(self._tls, "base_depth", 0) + depth
        if total_depth > MAX_XSHARD_DEPTH:
            return EVMResult(False, gas_left=gas,
                             error="cross-shard call depth exceeded")
        resp: EVMResult = external(DmcMessage(
            kind=MSG_CALL, context_id=self._tls.context_id, seq=0,
            to=to, caller=caller, data=data, gas=gas, static=static,
            depth=total_depth))
        return resp

    # -- overlays ----------------------------------------------------------
    def overlay(self, ctx: int, base: StateStorage) -> StateStorage:
        ov = self._overlays.get(ctx)
        if ov is None:
            ov = self._overlays[ctx] = StateStorage(base)
        return ov

    def merge(self, ctx: int, base: StateStorage) -> None:
        ov = self._overlays.pop(ctx, None)
        if ov is None:
            return
        for (table, key), entry in ov.changeset().items():
            if entry.deleted:
                base.remove(table, key)
            else:
                base.set(table, key, entry.value)

    def discard(self, ctx: int) -> None:
        self._overlays.pop(ctx, None)

    # -- executive bodies --------------------------------------------------
    def start_root(self, msg: DmcMessage, base: StateStorage,
                   block_number: int, timestamp: int) -> _Executive:
        ov = self.overlay(msg.context_id, base)

        def run(external):
            self._tls.external = external
            self._tls.context_id = msg.context_id
            self._tls.base_depth = 0
            try:
                return self.executor.execute_transaction(
                    msg.tx, ov, block_number, timestamp)
            finally:
                self._tls.external = None

        return _Executive(run)

    def start_subcall(self, msg: DmcMessage, base: StateStorage,
                      block_number: int, timestamp: int) -> _Executive:
        ov = self.overlay(msg.context_id, base)

        def run(external):
            self._tls.external = external
            self._tls.context_id = msg.context_id
            self._tls.base_depth = msg.depth
            try:
                env = self.executor._env(msg.caller, block_number,
                                         timestamp, msg.gas)
                # each cross-shard segment is its own EIP-2929 context:
                # message boundaries are deterministic across nodes,
                # thread-local warmth from earlier segments is not
                self.executor.evm.begin_tx_access(msg.caller, msg.to,
                                                  env.coinbase)
                return self.executor.evm.execute_message(
                    ov, env, msg.caller, msg.to, msg.value, msg.data,
                    msg.gas, depth=1, static=msg.static)
            finally:
                self._tls.external = None

        return _Executive(run)


class DmcRoundScheduler:
    """Routes DmcMessages between shard executors until every context
    finishes; detects and reverts deadlocked contexts."""

    def __init__(self, shards: Sequence[ShardExecutor]):
        self.shards = list(shards)
        if self.shards and not any(sh.precompile_home for sh in self.shards):
            self.shards[0].precompile_home = True

    def _shard_for(self, addr: bytes) -> Optional[ShardExecutor]:
        for sh in self.shards:
            if sh._is_local(addr):
                return sh
        return None  # unowned: the scheduler fails the message (a fallback
        # shard would re-externalize the same call forever)

    def execute_block(self, txs: Sequence[Transaction], base: StateStorage,
                      block_number: int, timestamp: int,
                      recorder: Optional[DmcStepRecorder] = None
                      ) -> list[Receipt]:
        receipts: list[Optional[Receipt]] = [None] * len(txs)
        # shard lock table: shard_id -> holding context (the GraphKeyLocks
        # grain here is the contract partition, the DMC sharding unit)
        lock_of: dict[bytes, int] = {}
        held: dict[int, set[bytes]] = {i: set() for i in range(len(txs))}
        # paused executives awaiting a response: (ctx, shard_id) -> stack
        frames: dict[int, list[tuple[ShardExecutor, _Executive]]] = {
            i: [] for i in range(len(txs))}
        reverts = 0

        ready: deque[DmcMessage] = deque(
            DmcMessage(kind=MSG_ROOT, context_id=i, seq=0, to=tx.to, tx=tx)
            for i, tx in enumerate(txs))
        blocked: list[DmcMessage] = []
        rounds = 0

        def step(sh: ShardExecutor, ctx: int, outcome: tuple[str, object],
                 ex: _Executive) -> None:
            """Advance one executive until it pauses or its frame ends."""
            kind, payload = outcome
            if kind == "call":
                # paused: route the request; response resumes this frame
                frames[ctx].append((sh, ex))
                sub: DmcMessage = payload  # type: ignore[assignment]
                sub.seq = len(frames[ctx])
                ready.append(sub)
                return
            # frame finished: pop to the caller frame, or finish the context
            if frames[ctx]:
                parent_sh, parent_ex = frames[ctx].pop()
                if kind == "error":
                    result = EVMResult(False, gas_left=0,
                                       error=f"executive: {payload}")
                else:
                    result = payload
                step(parent_sh, ctx, parent_ex.resume(result), parent_ex)
                return
            # root frame done -> context complete
            if kind == "error":
                rc = Receipt(block_number=block_number)
                rc.status = int(TransactionStatus.EXECUTION_ABORTED)
                rc.message = f"executive: {payload}"
                receipts[ctx] = rc
            else:
                receipts[ctx] = payload  # type: ignore[assignment]
            # TRANSACTION atomicity across shards: merge overlays only when
            # the root tx succeeded; a reverted/aborted tx discards every
            # shard's writes, including remote callees'. (Frame-granular
            # rollback of a cross-shard sub-call whose ENCLOSING frame later
            # reverts inside a successful tx would need the reference's
            # per-seq revert messages — not modeled; contracts share state
            # across shards at tx granularity.)
            if receipts[ctx] is not None and receipts[ctx].status == 0:
                for shard in self.shards:
                    shard.merge(ctx, base)
            else:
                for shard in self.shards:
                    shard.discard(ctx)
            for sid in held[ctx]:
                if lock_of.get(sid) == ctx:
                    del lock_of[sid]
            held[ctx].clear()

        def revert(ctx: int) -> None:
            """Abort a context's executives and requeue its root tx."""
            nonlocal reverts
            reverts += 1
            for _sh, ex in reversed(frames[ctx]):
                ex.abort()
            frames[ctx].clear()
            for shard in self.shards:
                shard.discard(ctx)
            for sid in held[ctx]:
                if lock_of.get(sid) == ctx:
                    del lock_of[sid]
            held[ctx].clear()
            ready.append(DmcMessage(kind=MSG_ROOT, context_id=ctx, seq=0,
                                    to=txs[ctx].to, tx=txs[ctx]))

        while ready:
            rounds += 1
            progressed = False
            work = deque(ready)
            ready.clear()
            still_blocked: list[DmcMessage] = []
            while work:
                msg = work.popleft()
                sh = self._shard_for(msg.to)
                ctx = msg.context_id
                if sh is None:  # no shard owns the destination address
                    progressed = True
                    if msg.kind == MSG_ROOT:
                        rc = Receipt(block_number=block_number)
                        rc.status = int(TransactionStatus.CALL_ADDRESS_ERROR)
                        rc.message = "no shard owns destination"
                        receipts[ctx] = rc
                    elif frames[ctx]:
                        p_sh, p_ex = frames[ctx].pop()
                        fail = EVMResult(False, gas_left=0,
                                         error="no shard owns destination")
                        step(p_sh, ctx, p_ex.resume(fail), p_ex)
                        while ready:
                            work.append(ready.popleft())
                    continue
                holder = lock_of.get(sh.shard_id)
                if holder is not None and holder != ctx:
                    still_blocked.append(msg)
                    continue
                lock_of[sh.shard_id] = ctx
                held[ctx].add(sh.shard_id)
                progressed = True
                if recorder is not None:  # determinism checksum per message
                    recorder.record_message(ctx, msg.seq, msg.to, msg.data)
                if msg.kind == MSG_ROOT:
                    ex = sh.start_root(msg, base, block_number, timestamp)
                else:
                    ex = sh.start_subcall(msg, base, block_number, timestamp)
                step(sh, ctx, ex.start(), ex)
                # messages generated during the step join this round's work
                while ready:
                    work.append(ready.popleft())
            if recorder is not None:
                recorder.next_round()
            # lock-blocked messages retry next round in deterministic order
            ready.extend(sorted(still_blocked,
                                key=lambda m: (m.context_id, m.seq)))
            if not progressed and ready:
                # every waiting message is lock-blocked: deadlock. Revert
                # the HIGHEST context id among the blocked (the reference's
                # victim rule); its locks free the survivors.
                victim = max(m.context_id for m in ready)
                ready = deque(m for m in ready if m.context_id != victim)
                LOG.warning(badge("DMC", "deadlock-revert", ctx=victim))
                revert(victim)

        metric("dmc.rounds", n=len(txs), rounds=rounds, reverts=reverts)
        for i, rc in enumerate(receipts):
            if rc is None:
                rc = Receipt(block_number=block_number)
                rc.status = int(TransactionStatus.EXECUTION_ABORTED)
                rc.message = "context never completed"
                receipts[i] = rc
        return [r for r in receipts]
