"""ExecPool — out-of-process execution workers behind the Scheduler seam.

The GIL attribution work (PERF r16) showed that even with columnar
admission, a node's execute stage still serialises Python opcode work
behind every other plane in the process: precompile dispatch, EVM
interpreter loops and receipt construction all hold THE one GIL that
ingest, crypto-lane host code, consensus and the RPC edge also need.
`services/executor_service.py` already proved the seam — ship encoded
txs, get back encoded receipts plus the state changeset, keep the 2PC
commit parent-side — but as a TCP service it targets Max-mode scale-out.
This module promotes the same seam to a LOCAL pool of spawn()ed worker
processes under the Scheduler (the Blockchain Machine's move of keeping
the ordering/commit plane on the host while the execution engine runs on
its own silicon, arxiv 2104.06968):

  * Each worker is a `multiprocessing` spawn process holding its own
    host-backend CryptoSuite + TransactionExecutor — a fresh interpreter
    with its OWN GIL, so execute no longer taxes the parent's.
  * Blocks ship as the raw wire frames the columnar substrate already
    has (`protocol.columnar` decodes them worker-side into views — the
    worker never builds per-tx dataclasses either), plus the
    admission-recovered senders so no worker re-runs signature recovery.
  * State reads are served BY THE PARENT over the pipe: the worker's
    StateStorage backend is a pipe proxy with a per-block cache. The
    protocol is stateless per block — no mirror to invalidate across
    speculative drops, 2PC rollbacks or snap-sync installs, which is
    exactly the class of bug a cached-mirror design breeds. The parent
    pump thread mostly sleeps in poll() (GIL released); each miss costs
    a dict/overlay lookup.
  * The 2PC, the roots and `ledger.prewrite_block` stay parent-side:
    `state_root` covers the prewrite rows (tx bodies, receipts, nonces)
    that only the parent can write, and the Merkle work is native and
    GIL-releasing anyway — moving it would ship the whole ledger for no
    GIL relief.

Failure model (the sanitize_ci --workers gate): a worker dying mid-block
(SIGKILL, OOM) fails only that EXEC — the scheduler falls back to
in-process execution for the block, the health plane flags
`scheduler.exec_worker` degraded, and the health ticker's probe respawns
the worker and clears the fault. Chain correctness never depends on the
pool: it is a pure offload.

With `workers > 1`, a block whose txs ALL carry conflict-key sets (the
same analysis DAG waves use) is sharded across workers by union-find
over conflict keys — disjoint shards touch disjoint state, so receipts
and changesets merge without coordination. Any opaque tx (no conflict
keys => must serialise) sends the whole block to one worker.
"""

from __future__ import annotations

import os
import threading
import time
from multiprocessing import get_context
from typing import Optional, Sequence

from ..codec.wire import Reader, Writer
from ..utils.log import LOG, badge, metric

# frame kinds (u8) — parent->worker: EXEC, READ_RESP, KEYS_RESP, PING;
# worker->parent: READ, KEYS, DONE, ERR, PONG
K_EXEC, K_READ, K_READ_RESP, K_KEYS, K_KEYS_RESP = 0, 1, 2, 3, 4
K_DONE, K_ERR, K_PING, K_PONG = 5, 6, 7, 8

EXEC_TIMEOUT = 120.0  # generous: a worker that can't finish a block in
#                       this long is treated exactly like a dead one
PING_TIMEOUT = 5.0


# ---------------------------------------------------------------------------
# worker-side (runs in the spawned child process)
# ---------------------------------------------------------------------------

class _PipeBackend:
    """Worker-side StateStorage backend: reads resolve over the pipe
    against the parent's live block backend (committed storage + the
    speculative changeset stack). Per-block cache — the protocol is
    stateless across blocks by design (see module docstring)."""

    def __init__(self, conn):
        self._conn = conn
        self._cache: dict = {}

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        tk = (table, key)
        if tk in self._cache:
            return self._cache[tk]
        self._conn.send_bytes(
            Writer().u8(K_READ).text(table).blob(key).bytes())
        r = Reader(self._conn.recv_bytes())
        if r.u8() != K_READ_RESP:
            raise RuntimeError("exec-worker: protocol desync on read")
        found = r.u8()
        val = r.blob() if found else None
        self._cache[tk] = val
        return val

    def keys(self, table: str, prefix: bytes = b""):
        self._conn.send_bytes(
            Writer().u8(K_KEYS).text(table).blob(prefix).bytes())
        r = Reader(self._conn.recv_bytes())
        if r.u8() != K_KEYS_RESP:
            raise RuntimeError("exec-worker: protocol desync on keys")
        return iter(r.seq(lambda rr: rr.blob()))

    def set(self, table: str, key: bytes, value: bytes) -> None:
        raise RuntimeError("exec-worker backend is read-only: writes "
                           "belong in the StateStorage overlay")

    def remove(self, table: str, key: bytes) -> None:
        raise RuntimeError("exec-worker backend is read-only: writes "
                           "belong in the StateStorage overlay")


def _exec_worker_main(conn, sm_crypto: bool) -> None:
    """Worker process entry (spawn target). One loop: EXEC in, DONE out,
    serving nothing else — crashes surface to the parent as a dead pipe."""
    # the worker executes Python opcode work; device backends belong to
    # the parent's crypto lane, and a spawned child must not try to grab
    # an accelerator of its own
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ..crypto.suite import make_suite
    from ..executor.executor import TransactionExecutor
    from ..protocol.columnar import decode_columns
    from ..services.storage_service import _write_changeset
    from ..storage.state import StateStorage

    suite = make_suite(sm_crypto, backend="host")
    executor = TransactionExecutor(suite)
    while True:
        try:
            frame = conn.recv_bytes()
        except (EOFError, OSError):
            return  # parent went away: exit quietly
        r = Reader(frame)
        kind = r.u8()
        if kind == K_PING:
            conn.send_bytes(Writer().u8(K_PONG).bytes())
            continue
        if kind != K_EXEC:
            conn.send_bytes(Writer().u8(K_ERR).text(
                f"unexpected frame kind {kind}").bytes())
            continue
        try:
            number = r.i64()
            timestamp = r.i64()
            wires = r.seq(lambda rr: rr.blob())
            senders = r.seq(lambda rr: rr.blob())
            cols = decode_columns(wires)
            txs = []
            for i in range(len(cols)):
                v = cols.view(i)
                if senders[i]:
                    v.set_sender(senders[i])
                txs.append(v)
            state = StateStorage(_PipeBackend(conn))
            receipts = executor.execute_block_dag(
                txs, state, number, timestamp)
            w = Writer().u8(K_DONE)
            w.seq(receipts, lambda ww, rc: ww.blob(rc.encode()))
            _write_changeset(w, state.changeset())
            conn.send_bytes(w.bytes())
        except (EOFError, OSError):
            return
        except Exception as exc:  # noqa: BLE001 — report, stay alive:
            # a poisonous block must not cost a respawn cycle
            try:
                conn.send_bytes(Writer().u8(K_ERR).text(repr(exc)).bytes())
            except OSError:
                return


# ---------------------------------------------------------------------------
# parent-side
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = ("proc", "conn", "alive", "lock", "busy_s", "blocks")

    def __init__(self, proc, conn):
        self.proc = proc
        self.conn = conn
        self.alive = True
        self.lock = threading.Lock()  # one EXEC in flight per worker
        self.busy_s = 0.0             # occupancy telemetry
        self.blocks = 0


class ExecPool:
    """Pool of out-of-process execution workers (see module docstring).

    Pure offload: `execute` returns None on ANY worker trouble and the
    caller (Scheduler._execute_locked) runs the block in-process. The
    health plane is informed either way; its probe respawns the dead."""

    def __init__(self, sm_crypto: bool = False, workers: int = 1,
                 health=None, registry=None):
        self.sm_crypto = bool(sm_crypto)
        self.n = max(1, int(workers))
        self.health = health
        from ..utils.metrics import REGISTRY
        self._reg = registry if registry is not None else REGISTRY
        self._ctx = get_context("spawn")
        self._workers: list[Optional[_Worker]] = [None] * self.n
        self._lock = threading.Lock()  # spawn/respawn bookkeeping
        self._started = False
        self._t_started = 0.0
        self._faulted = False
        self._fallbacks = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            self._t_started = time.monotonic()
            for i in range(self.n):
                self._spawn_locked(i)
        metric("exec_pool.start", workers=self.n,
               pids=[w.proc.pid for w in self._workers if w])

    def _spawn_locked(self, i: int) -> bool:
        try:
            parent_conn, child_conn = self._ctx.Pipe(duplex=True)
            proc = self._ctx.Process(
                target=_exec_worker_main, args=(child_conn, self.sm_crypto),
                name=f"exec-worker-{i}", daemon=True)
            proc.start()
            child_conn.close()
            self._workers[i] = _Worker(proc, parent_conn)
            return True
        except Exception:
            LOG.exception(badge("EXECPOOL", "spawn-failed", idx=i))
            self._workers[i] = None
            return False

    def stop(self) -> None:
        with self._lock:
            self._started = False
            workers, self._workers = self._workers, [None] * self.n
        for w in workers:
            if w is None:
                continue
            try:
                w.conn.close()
            except OSError:
                pass
            w.proc.terminate()
        for w in workers:
            if w is not None:
                w.proc.join(timeout=5)

    def pids(self) -> list[int]:
        """Live worker PIDs (the chaos smoke SIGKILLs one of these)."""
        with self._lock:
            return [w.proc.pid for w in self._workers
                    if w is not None and w.alive and w.proc.is_alive()]

    def stats(self) -> dict:
        """Worker-occupancy telemetry for chain_bench / node status."""
        wall = max(1e-9, time.monotonic() - self._t_started) \
            if self._t_started else 1e-9
        with self._lock:
            per = [{"pid": w.proc.pid if w else None,
                    "alive": bool(w and w.alive and w.proc.is_alive()),
                    "blocks": w.blocks if w else 0,
                    "busy_s": round(w.busy_s, 4) if w else 0.0,
                    "occupancy": round(min(1.0, w.busy_s / wall), 4)
                    if w else 0.0}
                   for w in self._workers]
        return {"workers": self.n, "fallbacks": self._fallbacks,
                "per_worker": per}

    # -- health ------------------------------------------------------------
    def _mark_dead(self, i: int, w: "_Worker", why: str) -> None:
        w.alive = False
        try:
            w.conn.close()
        except OSError:
            pass
        LOG.error(badge("EXECPOOL", "worker-died", idx=i,
                        pid=w.proc.pid, why=why))
        self._reg.inc("bcos_exec_worker_deaths_total")
        if self.health is not None:
            self._faulted = True
            self.health.degraded("scheduler.exec_worker",
                                 f"worker {i} (pid {w.proc.pid}): {why}",
                                 probe=self.probe_respawn)

    def probe_respawn(self) -> bool:
        """Health-plane probe: respawn any dead worker, verify the pool
        answers pings. True = healed (fault cleared by the ticker)."""
        ok = True
        with self._lock:
            if not self._started:
                return True  # stopped pool is not a fault
            for i, w in enumerate(self._workers):
                if w is not None and w.alive and w.proc.is_alive():
                    continue
                if w is not None and w.proc.is_alive():
                    w.proc.terminate()
                if not self._spawn_locked(i):
                    ok = False
        if not ok:
            return False
        for i, w in enumerate(list(self._workers)):
            if w is None:
                return False
            with w.lock:
                try:
                    w.conn.send_bytes(Writer().u8(K_PING).bytes())
                    if not w.conn.poll(PING_TIMEOUT):
                        raise TimeoutError("ping timeout")
                    if Reader(w.conn.recv_bytes()).u8() != K_PONG:
                        raise RuntimeError("bad pong")
                except Exception:  # noqa: BLE001 — probe verdict only
                    w.alive = False
                    return False
        metric("exec_pool.respawned", workers=self.n)
        return True

    # -- execution ---------------------------------------------------------
    def execute(self, txs: Sequence, backend, number: int, timestamp: int,
                suite, executor) -> Optional[tuple[list, dict]]:
        """Run a block on the pool. -> (receipts, changeset) or None (any
        worker trouble; caller falls back in-process). `backend` is the
        block's read view (committed storage or the speculative stack);
        `suite`/`executor` are the PARENT's — used only for sender
        backfill and shard planning, never for execution."""
        if not self._started or not txs:
            return None
        # senders ship with the frames so no worker re-runs recovery; the
        # batch call is a no-op when admission already populated them
        # (sync-replayed blocks are the cache-miss case)
        if any(getattr(t, "_sender", None) is None for t in txs):
            from ..protocol import batch_recover_senders
            batch_recover_senders(list(txs), suite)
        shards = self._plan_shards(txs, backend, executor)
        if shards is None or not shards:
            return None
        results: list = [None] * len(shards)
        if len(shards) == 1:
            results[0] = self._run_shard(shards[0][0], shards[0][1], txs,
                                         backend, number, timestamp)
        else:
            threads = []
            for si, (wi, idxs) in enumerate(shards):
                th = threading.Thread(
                    target=lambda si=si, wi=wi, idxs=idxs:
                        results.__setitem__(
                            si, self._run_shard(wi, idxs, txs, backend,
                                                number, timestamp)),
                    name=f"exec-pump-{si}", daemon=True)
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        if any(r is None for r in results):
            # partial results are DISCARDED whole: receipts/changeset
            # merging with an in-process retry of just the failed shard
            # would have to prove read isolation against the completed
            # shards — the fallback re-executes everything instead
            self._fallbacks += 1
            self._reg.inc("bcos_exec_pool_fallbacks_total")
            return None
        receipts: list = [None] * len(txs)
        changes: dict = {}
        for (wi, idxs), (rcs, cs) in zip(shards, results):
            for j, i in enumerate(idxs):
                receipts[i] = rcs[j]
            changes.update(cs)  # disjoint by conflict-key partitioning
        return receipts, changes

    def _plan_shards(self, txs, backend, executor
                     ) -> Optional[list[tuple[int, list[int]]]]:
        """-> [(worker_idx, [tx indices])] or None (no live worker).
        Single live worker (or any opaque tx) => one shard with every tx;
        otherwise union-find over conflict keys, exactly the disjointness
        DAG waves already rely on."""
        live = [i for i, w in enumerate(self._workers)
                if w is not None and w.alive]
        if not live:
            return None
        if len(live) == 1 or len(txs) < 2:
            return [(live[0], list(range(len(txs))))]
        from ..storage.state import StateStorage
        probe = StateStorage(backend)
        parent: dict[int, int] = {i: i for i in range(len(txs))}

        def find(a: int) -> int:
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        key_owner: dict[bytes, int] = {}
        for i, tx in enumerate(txs):
            try:
                keys = executor._conflict_keys(tx, probe)
            except Exception:  # noqa: BLE001 — analysis only
                keys = None
            if keys is None:  # opaque: must serialise with everything
                return [(live[0], list(range(len(txs))))]
            for k in keys:
                o = key_owner.get(k)
                if o is None:
                    key_owner[k] = i
                else:
                    ra, rb = find(o), find(i)
                    if ra != rb:
                        parent[rb] = ra
        groups: dict[int, list[int]] = {}
        for i in range(len(txs)):
            groups.setdefault(find(i), []).append(i)
        comps = sorted(groups.values(), key=len, reverse=True)
        if len(comps) == 1:
            return [(live[0], list(range(len(txs))))]
        # greedy longest-processing-time assignment onto the live workers
        buckets: list[list[int]] = [[] for _ in live]
        loads = [0] * len(live)
        for comp in comps:
            b = loads.index(min(loads))
            buckets[b].extend(comp)
            loads[b] += len(comp)
        return [(live[b], sorted(idxs))
                for b, idxs in enumerate(buckets) if idxs]

    def _run_shard(self, wi: int, idxs: list[int], txs, backend,
                   number: int, timestamp: int
                   ) -> Optional[tuple[list, dict]]:
        """Ship one shard to worker `wi` and pump its reads until DONE.
        -> (receipts, changeset) aligned with `idxs`, or None."""
        from ..protocol import Receipt
        from ..services.storage_service import _read_changeset
        with self._lock:
            w = self._workers[wi]
        if w is None or not w.alive:
            return None
        t0 = time.monotonic()
        with w.lock:
            if not w.alive:
                return None
            try:
                fr = Writer().u8(K_EXEC).i64(number).i64(timestamp)
                fr.seq([txs[i] for i in idxs],
                       lambda ww, t: ww.blob(t.encode()))
                fr.seq([txs[i] for i in idxs],
                       lambda ww, t: ww.blob(
                           getattr(t, "_sender", None) or b""))
                w.conn.send_bytes(fr.bytes())
                deadline = time.monotonic() + EXEC_TIMEOUT
                while True:
                    if not w.conn.poll(min(1.0, max(0.0, deadline
                                                    - time.monotonic()))):
                        if time.monotonic() >= deadline:
                            raise TimeoutError(
                                f"exec timeout after {EXEC_TIMEOUT}s")
                        if not w.proc.is_alive():
                            raise EOFError("worker process exited")
                        continue
                    r = Reader(w.conn.recv_bytes())
                    kind = r.u8()
                    if kind == K_READ:
                        table, key = r.text(), r.blob()
                        val = backend.get(table, key)
                        resp = Writer().u8(K_READ_RESP)
                        resp.u8(1 if val is not None else 0)
                        resp.blob(val if val is not None else b"")
                        w.conn.send_bytes(resp.bytes())
                    elif kind == K_KEYS:
                        table, prefix = r.text(), r.blob()
                        ks = list(backend.keys(table, prefix))
                        resp = Writer().u8(K_KEYS_RESP)
                        resp.seq(ks, lambda ww, k: ww.blob(k))
                        w.conn.send_bytes(resp.bytes())
                    elif kind == K_DONE:
                        receipts = [Receipt.decode(b)
                                    for b in r.seq(lambda rr: rr.blob())]
                        changes = _read_changeset(r)
                        dt = time.monotonic() - t0
                        w.busy_s += dt
                        w.blocks += 1
                        self._reg.observe("bcos_exec_worker_seconds", dt)
                        return receipts, changes
                    elif kind == K_ERR:
                        LOG.error(badge("EXECPOOL", "worker-exec-error",
                                        number=number, error=r.text()))
                        return None  # worker is fine, the block is not:
                        #              fall back without killing it
                    else:
                        raise RuntimeError(f"protocol desync: kind {kind}")
            except (EOFError, OSError, TimeoutError, RuntimeError) as exc:
                self._mark_dead(wi, w, repr(exc))
                return None
