"""Scheduler — drives a block through execute -> roots -> 2PC commit.

Reference counterpart: /root/reference/bcos-scheduler/src/SchedulerImpl.cpp
(:125 executeBlock, :370 commitBlock) and BlockExecutive.cpp (:52 prepare,
:380 asyncExecute, :1124 txsRoot/receiptsRoot, :1265 batchBlockCommit 2PC).

The execute phase fills the proposal's txs from the txpool
(BlockExecutive.cpp:324 asyncFillBlock), runs the executor (DAG waves), then
computes the three roots — txs/receipts via the TPU Merkle kernel, state root
over the changeset — and returns the finalised header for consensus
checkpointing. `commit` stages ledger writes + execution state into one
changeset and drives prepare/commit on the transactional storage.

Blocks execute strictly in order (block N+1 waits for N's header hash); the
pipeline overlap happens a level up, in consensus (PBFT pipelines proposals,
PBFTConfig waterlines) — matching the reference's design where the scheduler
serialises execution per block.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Optional, Sequence

from ..executor.executor import TransactionExecutor
from ..ledger.ledger import Ledger
from ..protocol import Block, BlockHeader, ParentInfo, Receipt, Transaction
from ..storage.interface import TransactionalStorage
from ..storage.state import StateStorage
from ..utils.log import LOG, badge, metric


@dataclasses.dataclass
class ExecutionResult:
    header: BlockHeader
    receipts: list[Receipt]
    state: StateStorage  # holds the block's execution changeset
    # the proposal's LIVE tx objects: their _sender fields were populated
    # by the admission/verify batch recover, so commit-time consumers
    # (the RPC cache's prime_block) can render senders without re-running
    # a recover batch over freshly-decoded copies
    txs: list = dataclasses.field(default_factory=list)


class Scheduler:
    def __init__(self, storage: TransactionalStorage, ledger: Ledger,
                 executor: TransactionExecutor, suite, txpool=None):
        self.storage = storage
        self.ledger = ledger
        self.executor = executor
        self.suite = suite
        self.txpool = txpool
        self._lock = threading.RLock()
        # cache: block hash -> ExecutionResult awaiting commit
        self._executed: dict[bytes, ExecutionResult] = {}
        # commit observers: callback(block_number) after a durable commit
        # (the reference's block-number notification fan-out,
        # Initializer.cpp:393-416). Observers run on a notifier thread so a
        # slow subscriber cannot stall the consensus commit path.
        self.on_commit: list = []
        # invalidation observers: callback(block_number) run SYNCHRONOUSLY
        # when previously-served state may no longer be trustworthy — a
        # commit 2PC rollback, or a snap-sync install that jumped the head
        # over wiped tables. The RPC query cache (rpc/cache.py) rides this:
        # it must be empty BEFORE any reader can observe the new state.
        self.on_invalidate: list = []
        # number -> the committed block's live txs, for commit observers
        # that want the sender-populated tx objects (RPC cache priming).
        # A few heights are kept because priming runs async on the
        # notifier thread and can lag a burst of commits.
        self.last_committed_txs: dict[int, list] = {}
        self._notify_q: "queue.Queue[Optional[int]]" = queue.Queue()
        self._notifier = threading.Thread(target=self._notify_loop,
                                          daemon=True, name="sched-notify")
        self._notifier.start()

    # -- execute (SchedulerImpl::executeBlock) -----------------------------
    def execute_block(self, block: Block, sealer_list: Sequence[bytes] | None = None
                      ) -> Optional[ExecutionResult]:
        """Execute a proposal; returns the finalised header (with roots) or
        None if the block cannot be executed (bad parent / missing txs)."""
        t0 = time.monotonic()
        with self._lock:
            header = block.header
            current = self.ledger.current_number()
            if header.number != current + 1:
                LOG.warning(badge("SCHED", "execute-out-of-order",
                                  number=header.number, current=current))
                return None
            parent = self.ledger.header_by_number(current)
            parent_hash = parent.hash(self.suite) if parent else b"\x00" * 32

            from ..utils.trace import block_trace
            trace = block_trace(header.number)
            txs = block.transactions
            if not txs and block.tx_hashes:
                if self.txpool is None:
                    return None
                txs = self.txpool.fill_block(block.tx_hashes)
                if txs is None:
                    LOG.warning(badge("SCHED", "missing-txs", number=header.number))
                    return None
                block.transactions = txs
            trace.stage("fill")

            state = StateStorage(self.storage)
            receipts = self.executor.execute_block_dag(
                txs, state, header.number, header.timestamp)
            trace.stage("execute")

            # finalise header: parent info + roots
            header.parent_info = [ParentInfo(current, parent_hash)]
            header.txs_root = block.calculate_txs_root(self.suite)
            block.receipts = receipts
            header.receipts_root = block.calculate_receipts_root(self.suite)
            self.ledger.prewrite_block(block, state)
            header.state_root = self.executor.state_root(state.changeset())
            trace.stage("roots")
            header.gas_used = sum(r.gas_used for r in receipts)
            header.invalidate()
            if sealer_list is not None:
                header.sealer_list = list(sealer_list)
            result = ExecutionResult(header, receipts, state,
                                     list(block.transactions))
            self._executed[header.hash(self.suite)] = result
            metric("scheduler.execute", number=header.number, n_tx=len(txs),
                   ms=int((time.monotonic() - t0) * 1000))
            return result

    # -- commit (SchedulerImpl::commitBlock; 2PC) --------------------------
    def commit_block(self, header: BlockHeader) -> bool:
        """Commit a previously-executed block (by header hash identity)."""
        t0 = time.monotonic()
        with self._lock:
            hh = header.hash(self.suite)
            result = self._executed.pop(hh, None)
            if result is None:
                LOG.error(badge("SCHED", "commit-unknown-block",
                                number=header.number))
                return False
            # persist the final header (with any commit seals collected)
            result.header.signature_list = header.signature_list
            st = result.state
            from ..ledger.ledger import T_HASH2NUM, T_HEADER, _be8
            st.set(T_HEADER, _be8(header.number), result.header.encode())
            st.set(T_HASH2NUM, hh, _be8(header.number))
            changes = st.changeset()
            try:
                self.storage.prepare(header.number, changes)
                self.storage.commit(header.number)
            except Exception:
                LOG.exception(badge("SCHED", "commit-2pc-failed",
                                    number=header.number))
                self.storage.rollback(header.number)
                # put the executed result back: a transient storage failure
                # must not strand the height (PBFT retries the checkpoint;
                # without this the node could only recover via block sync)
                self._executed[hh] = result
                self._fire_invalidate(header.number)
                return False
            # drop any other stale executed results for this height
            for h in [h for h, r in self._executed.items()
                      if r.header.number <= header.number]:
                self._executed.pop(h, None)
            # hand the committed block's LIVE txs (senders already
            # recovered at admission/verify) to the commit observers —
            # prime_block renders the senders row from these instead of
            # re-recovering freshly-decoded copies
            self.last_committed_txs[header.number] = result.txs
            while len(self.last_committed_txs) > 8:
                self.last_committed_txs.pop(min(self.last_committed_txs))
        if self.txpool is not None:
            tx_hashes = self.ledger.tx_hashes_by_number(header.number)
            nonces = self.ledger.nonces_by_number(header.number)
            self.txpool.on_block_committed(header.number, tx_hashes, nonces)
        self._notify_q.put(header.number)
        from ..utils.trace import drop_block_trace
        trace = drop_block_trace(header.number)
        if trace is not None:
            trace.finish()
        metric("scheduler.commit", number=header.number,
               ms=int((time.monotonic() - t0) * 1000))
        return True

    def external_commit(self, number: int) -> None:
        """The chain advanced OUTSIDE the execute/commit pipeline (snapshot
        install jumped the ledger to a checkpoint height): drop execution
        results the jump obsoleted, reconcile the txpool (per-block commit
        notifications never ran for the jumped range) and fan out the
        commit notification so eventsub/consensus observers see the new
        height."""
        with self._lock:
            for h in [h for h, r in self._executed.items()
                      if r.header.number <= number]:
                self._executed.pop(h, None)
            # the stash refers to the pre-install chain — a same-number
            # block on the installed chain must not reuse its senders
            self.last_committed_txs.clear()
        # BEFORE the commit notification fans out: a reader woken by the
        # new height must never be served a pre-install cache entry
        self._fire_invalidate(number)
        if self.txpool is not None:
            self.txpool.on_snapshot_installed(number)
        self._notify_q.put(number)
        metric("scheduler.external_commit", number=number)

    def invalidate_caches(self, number: int) -> None:
        """Public entry for subsystems that are ABOUT to mutate served
        state outside the commit pipeline (snap-sync install): wipes the
        on_invalidate observers' caches before the mutation publishes."""
        self._fire_invalidate(number)

    def _fire_invalidate(self, number: int) -> None:
        for cb in list(self.on_invalidate):
            try:
                cb(number)
            except Exception:
                LOG.exception(badge("SCHED", "invalidate-observer-failed",
                                    number=number))

    def shutdown(self) -> None:
        """Stop the notifier thread (node shutdown)."""
        self._notify_q.put(None)

    def _notify_loop(self) -> None:
        while True:
            number = self._notify_q.get()
            if number is None:
                return
            for cb in list(self.on_commit):
                try:
                    cb(number)
                except Exception:
                    LOG.exception(badge("SCHED", "commit-observer-failed",
                                        number=number))

    def drop_executed(self, header: BlockHeader) -> None:
        """Discard a cached execution result (failed sync replay etc.)."""
        with self._lock:
            self._executed.pop(header.hash(self.suite), None)

    # -- read-only call (SchedulerImpl::call) ------------------------------
    def call(self, tx: Transaction) -> Receipt:
        state = StateStorage(self.storage)
        n = self.ledger.current_number()
        return self.executor.execute_transaction(
            tx, state, n, int(time.time() * 1000))
