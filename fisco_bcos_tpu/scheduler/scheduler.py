"""Scheduler — drives blocks through execute -> roots -> 2PC commit as a
multi-stage pipeline across heights.

Reference counterpart: /root/reference/bcos-scheduler/src/SchedulerImpl.cpp
(:125 executeBlock, :370 commitBlock) and BlockExecutive.cpp (:52 prepare,
:380 asyncExecute, :1124 txsRoot/receiptsRoot, :1265 batchBlockCommit 2PC).

The execute phase fills the proposal's txs from the txpool
(BlockExecutive.cpp:324 asyncFillBlock), runs the executor (DAG waves), then
computes the three roots — txs/receipts via the TPU Merkle kernel, state root
over the changeset — and returns the finalised header for consensus
checkpointing. `commit` stages ledger writes + execution state into one
changeset and drives prepare/commit on the transactional storage.

Pipelining (the hardware-assisted-BFT shape: keep the accelerator fed by
overlapping stages instead of serialising them on one thread):

  * **Commit stage on its own thread.** `commit_async` hands a decided
    block to a dedicated commit worker; the consensus worker returns to
    draining packets immediately instead of blocking on the 2PC + WAL
    fsync. Commits stay strictly height-ordered (the worker refuses
    anything but committed+1).
  * **Speculative execution.** Block N+1 executes while N's commit is in
    flight: its StateStorage overlay reads through a StackedStorageView
    over N's (and any earlier uncommitted) changeset. Each block's
    `state_root` stays the Merkle root of ITS OWN changeset (it is NOT
    cumulative), so speculation changes nothing about header identity.
    The speculative chain (`_spec`) links by parent hash; a commit whose
    parent check fails, a 2PC rollback, or `abort_speculation` (view
    change) discards the speculative tail and execution re-runs against
    the durable head.

Blocks still execute strictly in order (N+1 chains on N's finalised
header); `pipeline=False` restores the serial execute-then-commit shape
for comparison benches and odd embeddings.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

from ..analysis import lockcheck as lc
from ..analysis.profiler import stage as _prof_stage
from ..executor.executor import TransactionExecutor
from ..ledger.ledger import Ledger
from ..protocol import Block, BlockHeader, ParentInfo, Receipt, Transaction
from ..storage.interface import ChangeSet, Entry, TransactionalStorage
from ..storage.state import StackedStorageView, StateStorage
from ..utils import failpoints as fp
from ..utils.log import LOG, badge, metric

# deterministic fault sites on the commit pipeline (utils/failpoints.py):
# `commit.entry` fires OUTSIDE commit_block's 2PC try (the uncaught
# commit-thread-exception path the health plane must catch), the `2pc.*`
# sites fire inside it (the clean rollback path)
fp.register("scheduler.commit.handoff", "scheduler.commit.entry",
            "scheduler.2pc.prepare", "scheduler.2pc.commit",
            "scheduler.2pc.rollback")


@dataclasses.dataclass
class ExecutionResult:
    header: BlockHeader
    receipts: list[Receipt]
    state: StateStorage  # holds the block's execution changeset
    # the proposal's LIVE tx objects: their _sender fields were populated
    # by the admission/verify batch recover, so commit-time consumers
    # (the RPC cache's prime_block) can render senders without re-running
    # a recover batch over freshly-decoded copies
    txs: list = dataclasses.field(default_factory=list)
    # the block's changeset snapshot: N+1's speculative reads stack over
    # it, and commit stages exactly it (plus the header rows) into the 2PC
    changes: ChangeSet = dataclasses.field(default_factory=dict)
    parent_hash: bytes = b""  # chain link checked again at commit time
    hh: bytes = b""           # header hash (commit identity key)
    committing: bool = False  # handed to the commit stage; abort keeps it
    t_executed: float = 0.0   # monotonic stamp for consensus-wait timing


class Scheduler:
    def __init__(self, storage: TransactionalStorage, ledger: Ledger,
                 executor: TransactionExecutor, suite, txpool=None,
                 pipeline: bool = True, trace_label: str = "",
                 health=None, state_index: bool = True):
        self.storage = storage
        self.ledger = ledger
        self.executor = executor
        self.suite = suite
        self.txpool = txpool
        self.pipeline = pipeline
        # ZK proof plane: persist each block's state-leaf digest index
        # (ledger.write_state_index) so getProof can serve changeset-
        # inclusion proofs anchored at state_root. The digests are a free
        # by-product of the root computation; the row is derived data the
        # root never covers, so mixed-setting fleets stay root-compatible.
        self.state_index = state_index
        # health plane (utils/health.py): commit failures degrade the node
        # (with a self-healing retry probe) instead of being swallowed
        self.health = health
        self._commit_faulted = False
        # out-of-process execution pool (scheduler/workers.py) — None =
        # in-process execute (the default); wired via attach_exec_pool
        self.exec_pool = None
        # per-node label for the block-trace registry + span attribution
        self.trace_label = trace_label
        self._lock = lc.make_rlock("scheduler.state")    # bookkeeping dicts
        self._exec_lock = lc.make_rlock("scheduler.exec")  # serialises execution
        self._commit_2pc = lc.make_lock("scheduler.2pc")   # serialises the 2PC
        # executed results awaiting commit: hash -> result, plus a height
        # index so eviction never rebuilds the whole dict under the lock
        self._executed: dict[bytes, ExecutionResult] = {}
        self._exec_heights: dict[int, set[bytes]] = {}
        # the speculative chain: contiguous heights committed+1..head, in
        # order; each entry's changeset backs the next height's reads
        self._spec: "OrderedDict[int, ExecutionResult]" = OrderedDict()
        # commit observers: callback(block_number) after a durable commit
        # (the reference's block-number notification fan-out,
        # Initializer.cpp:393-416). Observers run on a notifier thread so a
        # slow subscriber cannot stall the consensus commit path.
        self.on_commit: list = []
        # invalidation observers: callback(block_number) run SYNCHRONOUSLY
        # when previously-served state may no longer be trustworthy — a
        # commit 2PC rollback, or a snap-sync install that jumped the head
        # over wiped tables. The RPC query cache (rpc/cache.py) rides this:
        # it must be empty BEFORE any reader can observe the new state.
        self.on_invalidate: list = []
        # number -> the committed block's live txs, for commit observers
        # that want the sender-populated tx objects (RPC cache priming).
        # Commits are strictly height-ordered, so an OrderedDict evicts
        # its oldest entry in O(1) instead of re-scanning for min().
        self.last_committed_txs: "OrderedDict[int, list]" = OrderedDict()
        # per-stage occupancy accounting (chain_bench --pipeline-profile)
        self._stage_s: dict[str, float] = {}
        self._stage_n: dict[str, int] = {}
        self._overlap_commits = 0      # 2PCs that ran while a block executed
        self._speculative_execs = 0    # executions stacked over uncommitted state
        self._exec_busy = False
        self._commit_busy = False
        self._notify_q: "queue.Queue[Optional[int]]" = queue.Queue()
        self._notifier = threading.Thread(target=self._notify_loop,
                                          daemon=True, name="sched-notify")
        # the commit stage: only materialised in pipeline mode — callers
        # probe `commit_async` (None = synchronous commit path)
        self.commit_async: Optional[Callable] = None
        self._commit_q: "queue.Queue" = queue.Queue()
        self._commit_thread: Optional[threading.Thread] = None
        if pipeline:
            self.commit_async = self._commit_async
            self._commit_thread = threading.Thread(
                target=self._commit_loop, daemon=True, name="sched-commit")
        # workers launch LAST, after every field above is assigned, so
        # neither loop can observe a partially-built scheduler. An explicit
        # owner-side start() is impractical here — the ctor has many
        # external construction sites (node init, tests, benches) and a
        # forgotten start() silently stalls commit notification.
        self._notifier.start()  # bcoslint: disable=thread-start-in-ctor
        if self._commit_thread is not None:
            self._commit_thread.start()  # bcoslint: disable=thread-start-in-ctor

    # -- stage accounting --------------------------------------------------
    def _stage(self, name: str, dt: float) -> None:
        with self._lock:
            self._stage_s[name] = self._stage_s.get(name, 0.0) + dt
            self._stage_n[name] = self._stage_n.get(name, 0) + 1

    def pipeline_stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._spec),
                "commit_queue": self._commit_q.qsize(),
                "overlap_commits": self._overlap_commits,
                "speculative_execs": self._speculative_execs,
                "stages": {k: {"seconds": round(v, 4),
                               "count": self._stage_n.get(k, 0)}
                           for k, v in sorted(self._stage_s.items())},
            }

    def reset_pipeline_stats(self) -> None:
        with self._lock:
            self._stage_s.clear()
            self._stage_n.clear()
            self._overlap_commits = 0
            self._speculative_execs = 0

    def commit_backlog(self) -> int:
        """Decided-but-uncommitted depth: the commit worker's queue plus
        any in-flight 2PC — the overload controller's commit-stage signal
        (utils/overload.py). Lock-free snapshot reads."""
        return self._commit_q.qsize() + (1 if self._commit_busy else 0)

    def pipeline_busy(self) -> bool:
        """True while a block is executing or awaiting/undergoing commit —
        the sealer's keep-filling signal (a proposal sealed now would only
        queue behind the pipeline, so it may as well grow)."""
        with self._lock:
            if self._spec:
                return True
        return self._exec_busy

    def next_executable(self) -> int:
        """The height the next execute_block call must carry: speculative
        head + 1, or committed + 1 when nothing is in flight (always
        committed + 1 with the pipeline disabled — no speculation)."""
        with self._lock:
            committed = self.ledger.current_number()
            if not self.pipeline:
                return committed + 1
            while self._spec and next(iter(self._spec)) <= committed:
                self._forget_locked(self._spec.popitem(last=False)[1])
            if self._spec:
                return max(next(reversed(self._spec)), committed) + 1
            return committed + 1

    # -- execute (SchedulerImpl::executeBlock) -----------------------------
    def execute_block(self, block: Block, sealer_list: Sequence[bytes] | None = None
                      ) -> Optional[ExecutionResult]:
        """Execute a proposal; returns the finalised header (with roots) or
        None if the block cannot be executed (bad parent / missing txs).

        With pipelining, the proposal may chain on a not-yet-committed
        parent: reads stack over the speculative chain's changesets."""
        t0 = time.monotonic()
        with self._exec_lock:
            self._exec_busy = True
            try:
                # profiler stage mark (analysis/profiler.py): samples of
                # whatever thread drives execution (sealer, PBFT worker,
                # sync) carry stage=execute — two dict writes per block
                with _prof_stage("execute"):
                    return self._execute_locked(block, sealer_list, t0)
            finally:
                self._exec_busy = False

    def _execute_locked(self, block: Block,
                        sealer_list: Sequence[bytes] | None,
                        t0: float) -> Optional[ExecutionResult]:
        header = block.header
        with self._lock:
            committed = self.ledger.current_number()
            while self._spec and next(iter(self._spec)) <= committed:
                self._forget_locked(self._spec.popitem(last=False)[1])
            # re-executing an in-flight height replaces the speculative
            # tail from there up (solo retry after a commit failure, or a
            # superseding proposal) — unless a replaced entry is already on
            # the commit stage, in which case its outcome decides first
            if committed < header.number and self._spec \
                    and header.number <= next(reversed(self._spec)):
                if any(r.committing for n, r in self._spec.items()
                       if n >= header.number):
                    LOG.warning(badge("SCHED", "execute-vs-commit-race",
                                      number=header.number))
                    return None
                self._drop_spec_from_locked(header.number)
            spec = list(self._spec.values())
        if not self.pipeline:
            # serial mode: never stack over uncommitted state — execution
            # strictly follows the durable head (the documented opt-out
            # and the --no-pipeline bench anchor)
            spec = []
        base_number = spec[-1].header.number if spec else committed
        if header.number != base_number + 1:
            LOG.warning(badge("SCHED", "execute-out-of-order",
                              number=header.number, current=committed,
                              spec_head=base_number))
            return None
        if spec:
            parent_hash = spec[-1].header.hash(self.suite)
            backend = StackedStorageView(self.storage,
                                         [r.changes for r in spec])
        else:
            parent = self.ledger.header_by_number(committed)
            parent_hash = parent.hash(self.suite) if parent else b"\x00" * 32
            backend = self.storage

        from ..utils.trace import block_trace
        trace = block_trace(header.number, owner=self.trace_label)
        txs = block.transactions
        if not txs and block.tx_hashes:
            if self.txpool is None:
                return None
            txs = self.txpool.fill_block(block.tx_hashes)
            if txs is None:
                LOG.warning(badge("SCHED", "missing-txs", number=header.number))
                return None
            block.transactions = txs
        trace.stage("fill")
        t_fill = time.monotonic()
        self._stage("fill", t_fill - t0)

        state = StateStorage(backend)
        receipts = self._execute_stage(txs, state, backend, header)
        trace.stage("execute")
        t_exec = time.monotonic()
        self._stage("execute", t_exec - t_fill)

        # finalise header: parent info + roots
        header.parent_info = [ParentInfo(header.number - 1, parent_hash)]
        header.txs_root = block.calculate_txs_root(self.suite)
        block.receipts = receipts
        header.receipts_root = block.calculate_receipts_root(self.suite)
        self.ledger.prewrite_block(block, state)
        changes = state.changeset()
        # per-CHANGESET root, deliberately NOT cumulative: identical whether
        # the parent's changeset is durable or still speculative
        if self.state_index:
            root, leaf_index = self.executor.state_root_with_leaves(changes)
            header.state_root = root
            # staged AFTER the root so the row never feeds its own tree;
            # re-export picks it up for the same 2PC commit
            self.ledger.write_state_index(state, header.number, leaf_index)
            changes = state.changeset()
        else:
            header.state_root = self.executor.state_root(changes)
        trace.stage("roots")
        header.gas_used = sum(r.gas_used for r in receipts)
        header.invalidate()
        if sealer_list is not None:
            header.sealer_list = list(sealer_list)
        hh = header.hash(self.suite)
        result = ExecutionResult(header, receipts, state,
                                 list(block.transactions), changes,
                                 parent_hash, hh,
                                 t_executed=time.monotonic())
        self._stage("roots", result.t_executed - t_exec)
        with self._lock:
            # re-validate the chain didn't move while we executed (a commit
            # popping the front is fine; an abort/external jump is not)
            committed2 = self.ledger.current_number()
            tail = (self._spec[next(reversed(self._spec))]
                    if self._spec else None)
            if tail is not None:
                valid = (tail.header.number == header.number - 1
                         and tail.hh == parent_hash)
            else:
                parent = self.ledger.header_by_number(header.number - 1)
                valid = (committed2 == header.number - 1
                         and parent is not None
                         and parent.hash(self.suite) == parent_hash)
            if not valid:
                metric("scheduler.execute_discarded", number=header.number)
                return None
            if spec:
                self._speculative_execs += 1
            if self._commit_busy:
                self._overlap_commits += 1
            self._executed[hh] = result
            self._exec_heights.setdefault(header.number, set()).add(hh)
            self._spec[header.number] = result
        metric("scheduler.execute", number=header.number, n_tx=len(txs),
               speculative=bool(spec),
               ms=int((time.monotonic() - t0) * 1000))
        return result

    def _execute_stage(self, txs, state: StateStorage, backend,
                       header: BlockHeader) -> list[Receipt]:
        """The execute cut point. With an attached ExecPool
        (scheduler/workers.py) the block runs OUT OF PROCESS — encoded
        txs ship to a worker interpreter with its own GIL, receipts and
        the changeset come back, and the changeset is replayed into this
        block's StateStorage overlay so everything downstream (prewrite,
        roots, 2PC staging) is byte-identical to the in-process path.
        The pool is a pure offload: any worker trouble returns None and
        the block executes in-process — chain liveness never depends on
        a worker process."""
        if self.exec_pool is not None:
            out = self.exec_pool.execute(txs, backend, header.number,
                                         header.timestamp, self.suite,
                                         self.executor)
            if out is not None:
                receipts, changes = out
                for (table, key), e in changes.items():
                    if e.deleted:
                        state.remove(table, key)
                    else:
                        state.set(table, key, e.value)
                return receipts
            metric("scheduler.exec_pool_fallback", number=header.number)
        return self.executor.execute_block_dag(
            txs, state, header.number, header.timestamp)

    def attach_exec_pool(self, pool) -> None:
        """Adopt an out-of-process execution pool (node init; also used
        by benches). Call before the first execute_block."""
        self.exec_pool = pool

    # -- bookkeeping helpers (all under self._lock) ------------------------
    def _forget_locked(self, result: ExecutionResult) -> None:
        self._executed.pop(result.hh, None)
        hs = self._exec_heights.get(result.header.number)
        if hs is not None:
            hs.discard(result.hh)
            if not hs:
                self._exec_heights.pop(result.header.number, None)

    def _drop_spec_from_locked(self, number: int) -> None:
        """Drop speculative results at `number` and above — their reads
        went through a changeset that is no longer part of the chain."""
        for n in [n for n in self._spec if n >= number]:
            self._forget_locked(self._spec.pop(n))

    def _evict_upto_locked(self, number: int) -> None:
        """Retire executed results at or below a committed height. The
        height index makes this O(heights retired), not O(results)."""
        for n in [n for n in self._exec_heights if n <= number]:
            for h in self._exec_heights.pop(n):
                self._executed.pop(h, None)
            self._spec.pop(n, None)

    def abort_speculation(self) -> int:
        """Discard the speculative chain (view change replaced the rounds,
        or sync needs the execution slot). Results already handed to the
        commit stage are KEPT — they hold a checkpoint quorum and will
        land; everything above them re-executes against the new chain.
        Returns the number of results dropped."""
        dropped = 0
        with self._lock:
            while self._spec:
                n = next(reversed(self._spec))
                r = self._spec[n]
                if r.committing:
                    break
                self._forget_locked(self._spec.pop(n))
                dropped += 1
        if dropped:
            metric("scheduler.speculation_aborted", dropped=dropped)
        return dropped

    # -- commit stage (SchedulerImpl::commitBlock; 2PC) --------------------
    def _commit_async(self, header: BlockHeader,
                      done: Optional[Callable[[bool], None]] = None) -> None:
        """Queue a decided block for the commit worker; `done(ok)` fires on
        completion. Strict height ordering comes from FIFO submission plus
        commit_block's committed+1 check."""
        fp.fire("scheduler.commit.handoff")
        with self._lock:
            r = self._executed.get(header.hash(self.suite))
            if r is not None:
                r.committing = True
        self._commit_q.put((header, done))

    def _commit_loop(self) -> None:
        try:
            self._commit_loop_inner()
        except BaseException as exc:
            # the dedicated commit thread DYING is fatal for the pipeline:
            # nothing will ever drain the queue again while the sealer
            # keeps granting — say so at the top of the health plane
            # instead of wedging silently
            LOG.critical(badge("SCHED", "commit-thread-died",
                               error=repr(exc)))
            if self.health is not None:
                self.health.failed("scheduler.commit_thread", repr(exc))
            raise

    def _commit_loop_inner(self) -> None:
        while True:
            item = self._commit_q.get()
            if item is None:
                return
            header, done = item
            try:
                # dynamic lookup so per-instance instrumentation wrappers
                # (benches, soak tests) see pipelined commits too
                ok = self.commit_block(header)
            except Exception as exc:
                # an exception ESCAPING commit_block used to leave the
                # pipeline silently wedged (the sealer still granting, the
                # height never landing): log loudly and trip the health
                # plane with the self-healing retry probe
                LOG.critical(badge("SCHED", "commit-thread-exception",
                                   number=header.number, error=repr(exc)))
                LOG.exception(badge("SCHED", "commit-worker-crashed",
                                    number=header.number))
                self._commit_fault(exc)
                ok = False
            if done is not None:
                try:
                    done(ok)
                except Exception:
                    LOG.exception(badge("SCHED", "commit-done-cb-failed",
                                        number=header.number))

    # -- health plumbing ---------------------------------------------------
    def report_commit_fault(self, exc: BaseException) -> None:
        """Public entry for embedders driving commit_block on their own
        thread (solo mode's proposal path): same degraded-with-retry-probe
        handling as the pipeline's commit worker."""
        self._commit_fault(exc)

    def _commit_fault(self, exc: BaseException) -> None:
        if self.health is None:
            return
        self._commit_faulted = True
        self.health.degraded("scheduler.commit", repr(exc),
                             probe=self.retry_pending_commit)

    def _commit_healthy(self) -> None:
        if self._commit_faulted:  # plain-flag guard on the happy path
            self._commit_faulted = False
            if self.health is not None:
                self.health.clear("scheduler.commit")

    def retry_pending_commit(self) -> bool:
        """Self-healing probe: re-drive the stalled height if a DECIDED
        execution result (it carries commit seals) is waiting at
        committed+1. True = healed (retry landed, or nothing is stuck)."""
        with self._lock:
            committed = self.ledger.current_number()
            result = None
            for h in self._exec_heights.get(committed + 1, ()):
                r = self._executed.get(h)
                if r is not None and not r.committing \
                        and r.header.signature_list:
                    result = r
                    break
        if result is None:
            return True  # nothing stuck: consensus/sync owns recovery now
        return self.commit_block(result.header)

    def commit_block(self, header: BlockHeader) -> bool:
        """Commit a previously-executed block (by header hash identity).
        Runs on the commit worker in pipeline mode; callable directly for
        sync replay, solo mode and service proxies."""
        hh = header.hash(self.suite)
        with self._lock:
            guard = self._executed.get(hh)
        try:
            with _prof_stage("commit"):
                return self._commit_block_inner(header, hh)
        except BaseException:
            # an exception ESCAPING the commit (injected fault, observer
            # bug) must not strand the result half-committed: without this
            # restore, `committing` stayed True forever, the retry probe
            # saw "nothing stuck", and the node wedged at the height until
            # sync rescued it (found by the failpoint matrix under load).
            # Mirror the 2PC-failure restore, and keep the DECIDED
            # header's commit seals so the retry can land it.
            if guard is not None:
                with self._lock:
                    guard.committing = False
                    if header.signature_list \
                            and not guard.header.signature_list:
                        guard.header.signature_list = header.signature_list
                    if self._executed.get(hh) is not guard \
                            and self.ledger.current_number() \
                            < guard.header.number:
                        self._executed[hh] = guard
                        self._exec_heights.setdefault(
                            guard.header.number, set()).add(hh)
            raise

    def _commit_block_inner(self, header: BlockHeader, hh: bytes) -> bool:
        t0 = time.monotonic()
        fp.fire("scheduler.commit.entry")
        with self._lock:
            result = self._executed.get(hh)
            if result is None:
                LOG.error(badge("SCHED", "commit-unknown-block",
                                number=header.number))
                return False
            committed = self.ledger.current_number()
        if result.header.number != committed + 1:
            # out of order (an earlier commit failed transiently, or sync
            # already passed this height): refuse WITHOUT dropping — a
            # retried predecessor re-enables this exact result
            LOG.error(badge("SCHED", "commit-out-of-order",
                            number=result.header.number, current=committed))
            return False
        parent = self.ledger.header_by_number(result.header.number - 1)
        parent_hash = parent.hash(self.suite) if parent else b"\x00" * 32
        if result.parent_hash and result.parent_hash != parent_hash:
            # built on a chain that lost: this result can never commit —
            # drop it and every speculative child stacked over it
            LOG.error(badge("SCHED", "commit-parent-mismatch",
                            number=result.header.number))
            with self._lock:
                self._drop_spec_from_locked(result.header.number)
                self._forget_locked(result)
            return False
        with self._lock:
            result.committing = True
            self._forget_locked(result)  # restored below on 2PC failure
        # persist the final header (with any commit seals collected)
        result.header.signature_list = header.signature_list
        number = result.header.number
        from ..ledger.ledger import T_HASH2NUM, T_HEADER, _be8
        changes = dict(result.changes)
        changes[(T_HEADER, _be8(number))] = Entry(result.header.encode())
        changes[(T_HASH2NUM, hh)] = Entry(_be8(number))
        from ..utils.trace import block_trace, drop_block_trace
        trace = block_trace(number, owner=self.trace_label)
        trace.stage("consensus_wait")
        if result.t_executed:
            self._stage("consensus_wait", t0 - result.t_executed)
        with self._commit_2pc:
            # re-check under the 2PC lock: a concurrent committer (sync
            # replay racing the commit worker) must not land a second
            # block at this height
            if self.ledger.current_number() != number - 1:
                LOG.error(badge("SCHED", "commit-raced", number=number))
                with self._lock:
                    result.committing = False
                    self._executed[hh] = result
                    self._exec_heights.setdefault(number, set()).add(hh)
                return False
            self._commit_busy = True
            try:
                fp.fire("scheduler.2pc.prepare")
                self.storage.prepare(number, changes)
                fp.fire("scheduler.2pc.commit")
                self.storage.commit(number)
            except Exception as exc:
                LOG.exception(badge("SCHED", "commit-2pc-failed",
                                    number=number))
                fp.fire("scheduler.2pc.rollback")
                self.storage.rollback(number)
                self._commit_fault(exc)
                # put the executed result back: a transient storage failure
                # must not strand the height (PBFT retries the checkpoint;
                # without this the node could only recover via block sync).
                # The speculative chain above it stays valid — it reads the
                # byte-identical preserved changeset.
                with self._lock:
                    result.committing = False
                    self._executed[hh] = result
                    self._exec_heights.setdefault(number, set()).add(hh)
                self._fire_invalidate(number)
                return False
            finally:
                self._commit_busy = False
        self._commit_healthy()
        if self._exec_busy:
            with self._lock:
                self._overlap_commits += 1
        trace.stage("commit")
        self._stage("commit", time.monotonic() - t0)
        with self._lock:
            # drop any other stale executed results for this height
            self._evict_upto_locked(number)
            # hand the committed block's LIVE txs (senders already
            # recovered at admission/verify) to the commit observers —
            # prime_block renders the senders row from these instead of
            # re-recovering freshly-decoded copies
            self.last_committed_txs[number] = result.txs
            while len(self.last_committed_txs) > 8:
                self.last_committed_txs.popitem(last=False)
        if self.txpool is not None:
            tx_hashes = self.ledger.tx_hashes_by_number(number)
            nonces = self.ledger.nonces_by_number(number)
            self.txpool.on_block_committed(number, tx_hashes, nonces)
        self._notify_q.put(number)
        # receipt waiters are settled by on_block_committed above: stamp
        # the notify stage before retiring the block's trace
        trace.stage("notify")
        tr = drop_block_trace(number, owner=self.trace_label)
        if tr is not None:
            tr.finish()
        metric("scheduler.commit", number=number,
               ms=int((time.monotonic() - t0) * 1000))
        return True

    def external_commit(self, number: int) -> None:
        """The chain advanced OUTSIDE the execute/commit pipeline (snapshot
        install jumped the ledger to a checkpoint height): drop every
        execution result (the speculative chain hangs off the pre-install
        head), reconcile the txpool (per-block commit notifications never
        ran for the jumped range) and fan out the commit notification so
        eventsub/consensus observers see the new height."""
        with self._lock:
            self._spec.clear()
            self._executed.clear()
            self._exec_heights.clear()
            # the stash refers to the pre-install chain — a same-number
            # block on the installed chain must not reuse its senders
            self.last_committed_txs.clear()
        # BEFORE the commit notification fans out: a reader woken by the
        # new height must never be served a pre-install cache entry
        self._fire_invalidate(number)
        if self.txpool is not None:
            self.txpool.on_snapshot_installed(number)
        self._notify_q.put(number)
        metric("scheduler.external_commit", number=number)

    def invalidate_caches(self, number: int) -> None:
        """Public entry for subsystems that are ABOUT to mutate served
        state outside the commit pipeline (snap-sync install): wipes the
        on_invalidate observers' caches before the mutation publishes."""
        self._fire_invalidate(number)

    def _fire_invalidate(self, number: int) -> None:
        for cb in list(self.on_invalidate):
            try:
                cb(number)
            except Exception:
                LOG.exception(badge("SCHED", "invalidate-observer-failed",
                                    number=number))

    def shutdown(self) -> None:
        """Stop the notifier + commit threads (node shutdown). Queued
        commits drain first — a decided block holds a checkpoint quorum
        and is cheap to land now versus a replay at next boot."""
        if self._commit_thread is not None:
            self._commit_q.put(None)
            self._commit_thread.join(timeout=10.0)
            self._commit_thread = None
        self._notify_q.put(None)

    def _notify_loop(self) -> None:
        while True:
            number = self._notify_q.get()
            if number is None:
                return
            for cb in list(self.on_commit):
                try:
                    cb(number)
                except Exception:
                    LOG.exception(badge("SCHED", "commit-observer-failed",
                                        number=number))

    def drop_executed(self, header: BlockHeader) -> None:
        """Discard a cached execution result (failed sync replay, round
        superseded mid-execution). Speculative children stacked over it are
        discarded too — their reads went through its changeset."""
        with self._lock:
            r = self._executed.get(header.hash(self.suite))
            if r is None:
                return
            self._forget_locked(r)
            if self._spec.get(r.header.number) is r:
                self._spec.pop(r.header.number)
                self._drop_spec_from_locked(r.header.number + 1)

    # -- read-only call (SchedulerImpl::call) ------------------------------
    def call(self, tx: Transaction) -> Receipt:
        state = StateStorage(self.storage)
        n = self.ledger.current_number()
        return self.executor.execute_transaction(
            tx, state, n, int(time.time() * 1000))
