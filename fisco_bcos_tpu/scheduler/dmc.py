"""DMC — deterministic multi-contract sharded block execution.

Reference counterpart: /root/reference/bcos-scheduler/src/DmcExecutor.h:38-80
(per-contract message queue: submit/prepare/go), BlockExecutive.cpp:861
DMCExecute (iterative rounds until every executor reports FINISHED), and
GraphKeyLocks.cpp (cross-contract key locks + deadlock revert). In the
reference this shards transactions **by contract address** across executor
processes (Max mode scales executors horizontally, TarsExecutorManager.cpp).

Determinism first (replicas must derive identical state roots), so the
design composes the reference's two mechanisms differently:

  1. **Static wave planning** (the DAG side, CriticalFields.h:45): txs are
     laid into waves such that any two txs in the same wave either share a
     shard (then they run serially, in block order) or have disjoint
     declared conflict keys (then order cannot matter). Txs whose key set
     is unknowable statically (EVM calls — they may CALL anywhere) are
     global barriers, exactly like the reference's non-parallelizable txs.
  2. **Runtime key locks** (GraphKeyLocks): each tx acquires its declared
     keys before executing — a failed acquisition inside a wave means the
     planner's disjointness was violated (a handler touched an undeclared
     key); the tx is deferred and re-run serially after the wave, in block
     order, so the result is still deterministic. This is the DMC
     revert-and-retry loop with the deadlock case planned away.

Shards execute concurrently (thread pool); per-tx state mutation is
serialised on a state lock because the overlay is shared — the structure
(per-shard serial queues + waves + key locks) is what carries over to the
Pro/Max split where shards become processes owning partitioned state.

Receipts return in block order; the changeset equals the serial schedule's.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

from ..protocol import Receipt, Transaction
from ..storage.state import StateStorage
from ..utils.log import LOG, badge, metric
from .keylocks import GraphKeyLocks


class DmcExecutor:
    """Wave-planned, shard-parallel execution over a TransactionExecutor."""

    def __init__(self, executor, suite, max_workers: int = 8):
        self.executor = executor
        self.suite = suite
        self.max_workers = max_workers

    # -- planning ----------------------------------------------------------
    def plan(self, txs: Sequence[Transaction]) -> list[list[int]]:
        """Waves of tx indices: same-wave txs are shard-serial or
        key-disjoint; opaque txs get singleton waves (global barriers)."""
        waves: list[list[int]] = []
        # per key: (wave of last toucher, its shard); waves are monotone
        last_of_key: dict[bytes, tuple[int, bytes]] = {}
        last_of_shard: dict[bytes, int] = {}
        barrier = -1
        for i, tx in enumerate(txs):
            keys = self.executor._conflict_keys(tx)
            if keys is None:
                w = len(waves)
                waves.append([i])
                barrier = w
                last_of_key.clear()
                last_of_shard.clear()
                continue
            # same shard may share a wave (serial, block order inside the
            # shard); a key shared across shards forces the next wave
            w = max(barrier + 1, last_of_shard.get(tx.to, 0))
            for k in keys:
                lw, lsh = last_of_key.get(k, (-1, tx.to))
                w = max(w, lw if lsh == tx.to else lw + 1)
            while w >= len(waves):
                waves.append([])
            waves[w].append(i)
            last_of_shard[tx.to] = w
            for k in keys:
                last_of_key[k] = (w, tx.to)
        return [wv for wv in waves if wv]

    # -- execution ---------------------------------------------------------
    def execute_block(self, txs: Sequence[Transaction], state: StateStorage,
                      block_number: int, timestamp: int) -> list[Receipt]:
        receipts: list[Optional[Receipt]] = [None] * len(txs)
        locks = GraphKeyLocks()
        state_lock = threading.RLock()
        waves = self.plan(txs)
        deferred_total = 0

        def run_one(i: int) -> bool:
            """Execute tx i if its declared keys are free; False = defer."""
            tx = txs[i]
            token = ("tx", i)
            keys = self.executor._conflict_keys(tx) or []
            # global key scope: declared keys already embed their table
            for k in sorted(keys):
                if not locks.try_acquire(token, b"", k):
                    locks.release_all(token)
                    return False
            try:
                with state_lock:
                    receipts[i] = self.executor.execute_transaction(
                        tx, state, block_number, timestamp)
                return True
            finally:
                locks.release_all(token)

        pool = ThreadPoolExecutor(max_workers=self.max_workers)
        try:
            for wave in waves:
                # group by shard; shards run concurrently, shard-serial inside
                by_shard: dict[bytes, list[int]] = {}
                for i in wave:
                    by_shard.setdefault(txs[i].to, []).append(i)
                deferred: list[int] = []
                dlock = threading.Lock()

                def run_shard(idxs: list[int]):
                    for i in idxs:
                        if not run_one(i):
                            with dlock:
                                deferred.append(i)

                if len(by_shard) <= 1:
                    for idxs in by_shard.values():
                        run_shard(idxs)
                else:
                    futs = [pool.submit(run_shard, idxs)
                            for idxs in by_shard.values()]
                    for f in futs:
                        f.result()
                # planner violation fallback: strictly serial, block order
                for i in sorted(deferred):
                    deferred_total += 1
                    with state_lock:
                        receipts[i] = self.executor.execute_transaction(
                            txs[i], state, block_number, timestamp)
        finally:
            pool.shutdown(wait=True)
        if deferred_total:
            LOG.warning(badge("DMC", "undeclared-conflicts",
                              n=deferred_total))
        metric("dmc.execute", n=len(txs), waves=len(waves),
               deferred=deferred_total)
        return [r for r in receipts]