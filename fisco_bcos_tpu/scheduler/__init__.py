"""Scheduler: per-block execution orchestration + commit 2PC (bcos-scheduler)."""

from .scheduler import ExecutionResult, Scheduler

__all__ = ["Scheduler", "ExecutionResult"]
