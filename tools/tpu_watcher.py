#!/usr/bin/env python3
"""TPU tunnel watcher: probe until healthy, then run the full device sweep.

The accelerator tunnel in this environment flaps — healthy for short
windows, wedged for hours (VERDICT r3 weak #1: a wedged tunnel at round
end erased the round's device evidence). This watcher:

  1. probes the default backend in a bounded subprocess every
     --probe-interval seconds;
  2. on the first healthy probe, launches benchmark/device_sweep.py in a
     bounded child (--sweep-timeout); the sweep persists incrementally to
     BENCH_LAST_GOOD.json, so even a wedge mid-sweep keeps partials;
  3. after a complete sweep, keeps watching and refreshes the sweep every
     --refresh-interval seconds while the tunnel stays healthy (so later
     kernel improvements get measured).

Run detached:  nohup python -u tools/tpu_watcher.py >> tpu_watcher.log &
Status file:   .tpu_watcher_status.json (probe history tail + state)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fisco_bcos_tpu.utils.backend import probe_default_backend  # noqa: E402


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def log(msg: str) -> None:
    print(f"[{_now()}] {msg}", flush=True)


def _run_bench(script: str, argv: list[str], key: str,
               timeout: float) -> dict | None:
    """Run a benchmark script on the healthy window, parse its one JSON
    line, merge it into BENCH_LAST_GOOD.json under `key`. Bounded;
    failures are logged, never fatal."""
    try:
        r = subprocess.run(
            [sys.executable, "-u",
             os.path.join(_REPO, "benchmark", script), *argv],
            cwd=_REPO, timeout=timeout, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        if r.returncode != 0:
            log(f"{script} failed rc={r.returncode}:\n"
                f"{(r.stdout or '')[-800:]}")
            return None
        lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
        if not lines:
            log(f"{script}: no JSON line in output")
            return None
        rec = json.loads(lines[-1])
        import bench as bench_mod

        def merge(lg):
            lg.setdefault("configs", {})[key] = {
                **rec, "measured_at":
                    time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
            return lg

        bench_mod.update_last_good(merge)
        return rec
    except Exception as exc:  # noqa: BLE001 — never kill the watcher
        log(f"{script} error: {type(exc).__name__}: {exc}")
        return None


def _run_profile() -> dict | None:
    """Per-kernel scan-step breakdown (VERDICT r3 #1)."""
    return _run_bench("profile_kernels.py", ["--json"], "profile", 1800)


def _run_ingest() -> dict | None:
    """BASELINE row 4: 50k mixed secp+SM2 ingest."""
    n = os.environ.get("SWEEP_INGEST_N", "50000")
    return _run_bench("ingest_bench.py", ["--mixed", "-n", n],
                      f"txpool_ingest_mixed_{n}", 2400)


def _run_chain_tps() -> dict | None:
    """BASELINE row 5: live 4-node PBFT chain TPS. Host-CPU-bound, so the
    bench host's cores (not this 1-core dev container) set the number."""
    n = os.environ.get("SWEEP_CHAIN_N", "6000")
    return _run_bench("chain_bench.py", ["-n", n, "--backend", "auto"],
                      "chain_tps_4node", 1800)


def _run_fused_check() -> dict | None:
    """Single-kernel end-to-end verify/recover/SM2 device validation +
    timing vs the default dispatch (VERDICT r4 #2: the fused-verify
    default flips only on a measured device win)."""
    return _run_bench("fused_check.py", [], "fused_check", 1800)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-interval", type=float, default=180.0)
    ap.add_argument("--probe-timeout", type=float, default=60.0)
    ap.add_argument("--sweep-timeout", type=float, default=2700.0)
    ap.add_argument("--refresh-interval", type=float, default=2400.0)
    ap.add_argument("--out", default=os.path.join(_REPO, "BENCH_LAST_GOOD.json"))
    args = ap.parse_args()

    status_path = os.path.join(_REPO, ".tpu_watcher_status.json")
    state = {"probes": 0, "healthy_probes": 0, "sweeps_ok": 0,
             "sweeps_failed": 0, "last_probe": None, "last_sweep": None}
    last_sweep_ok_at = 0.0

    log(f"watcher start: probe every {args.probe_interval:.0f}s, "
        f"sweep timeout {args.sweep_timeout:.0f}s")
    while True:
        healthy, diag, ndev = probe_default_backend(
            timeout=args.probe_timeout, cwd=_REPO)
        state["probes"] += 1
        state["last_probe"] = {"at": _now(), "healthy": healthy,
                               "diag": diag, "n_devices": ndev}
        if healthy:
            state["healthy_probes"] += 1
            log(f"probe: HEALTHY platform={diag} n={ndev}")
            # monotonic, not wall clock: an NTP step used to be able to
            # suppress (or force) a sweep for hours (bcoslint
            # wallclock-deadline finding)
            fresh_needed = (time.monotonic() - last_sweep_ok_at
                            > args.refresh_interval)
            if fresh_needed:
                log("launching device sweep "
                    f"(timeout {args.sweep_timeout:.0f}s)")
                try:
                    r = subprocess.run(
                        [sys.executable, "-u",
                         os.path.join(_REPO, "benchmark", "device_sweep.py"),
                         "--out", args.out],
                        cwd=_REPO, timeout=args.sweep_timeout,
                        stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True)
                    tail = (r.stdout or "")[-2000:]
                    sweep_ok = r.returncode == 0
                    if sweep_ok:
                        state["sweeps_ok"] += 1
                        last_sweep_ok_at = time.monotonic()
                        log(f"sweep OK:\n{tail}")
                        prof = _run_profile()
                        if prof:
                            log(f"profile OK: {prof}")
                        self_ingest = _run_ingest()
                        if self_ingest:
                            log(f"ingest OK: {self_ingest}")
                        tps = _run_chain_tps()
                        if tps:
                            log(f"chain TPS OK: {tps}")
                        fused = _run_fused_check()
                        if fused:
                            log(f"fused check OK: {fused}")
                    else:
                        state["sweeps_failed"] += 1
                        log(f"sweep FAILED rc={r.returncode}:\n{tail}")
                except subprocess.TimeoutExpired as exc:
                    sweep_ok = False
                    state["sweeps_failed"] += 1
                    partial = ((exc.stdout or b"")
                               if isinstance(exc.stdout, (bytes, str))
                               else b"")
                    if isinstance(partial, bytes):
                        partial = partial.decode("utf-8", "replace")
                    log(f"sweep TIMED OUT after {args.sweep_timeout:.0f}s "
                        f"(wedge mid-sweep; partials kept):\n"
                        f"{partial[-2000:]}")
                state["last_sweep"] = {"at": _now(), "ok": sweep_ok}
        else:
            log(f"probe: unhealthy ({diag})")
        try:
            with open(status_path, "w") as f:
                json.dump(state, f, indent=1)
        except Exception:
            pass
        time.sleep(args.probe_interval)


if __name__ == "__main__":
    main()
