#!/usr/bin/env python3
"""archive-tool — move historical block data out of hot storage.

Reference counterpart: /root/reference/tools/archive-tool (archives block
bodies/receipts below a height out of RocksDB into cold storage and
deletes them from the node, keeping headers so proofs/sync anchors remain).

Commands (node must be stopped):
  archive <path> <archive-file> --until N   export blocks [1, N) bodies
          (txs, receipts, nonces, num->txs) then delete them from storage
  restore <path> <archive-file>             re-import archived bodies
  info    <archive-file>                    show archive contents

The archive format is a length-prefixed record stream:
  u16 table_len | table | u32 key_len | key | u32 val_len | value
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fisco_bcos_tpu.codec.wire import Reader  # noqa: E402
from fisco_bcos_tpu.ledger.ledger import (  # noqa: E402
    T_NONCES,
    T_NUM2TXS,
    T_RECEIPT,
    T_TX,
)
from fisco_bcos_tpu.storage.wal import WalStorage  # noqa: E402


def _be8(n: int) -> bytes:
    return n.to_bytes(8, "big")


def _write_record(f, table: str, key: bytes, value: bytes) -> None:
    tb = table.encode()
    f.write(struct.pack(">H", len(tb)) + tb
            + struct.pack(">I", len(key)) + key
            + struct.pack(">I", len(value)) + value)


def _read_records(path: str):
    with open(path, "rb") as f:
        while True:
            head = f.read(2)
            if not head:
                return
            (tl,) = struct.unpack(">H", head)
            table = f.read(tl).decode()
            (kl,) = struct.unpack(">I", f.read(4))
            key = f.read(kl)
            (vl,) = struct.unpack(">I", f.read(4))
            value = f.read(vl)
            yield table, key, value


def archive(path: str, out: str, until: int) -> None:
    st = WalStorage(path)
    try:
        n_blocks = n_records = 0
        with open(out, "wb") as f:
            for number in range(1, until):
                raw = st.get(T_NUM2TXS, _be8(number))
                if raw is None:
                    continue
                n_blocks += 1
                _write_record(f, T_NUM2TXS, _be8(number), raw)
                n_records += 1
                tx_hashes = Reader(raw).seq(lambda r: r.blob())
                for h in tx_hashes:
                    for table in (T_TX, T_RECEIPT):
                        v = st.get(table, h)
                        if v is not None:
                            _write_record(f, table, h, v)
                            n_records += 1
                nv = st.get(T_NONCES, _be8(number))
                if nv is not None:
                    _write_record(f, T_NONCES, _be8(number), nv)
                    n_records += 1
        # delete AFTER the archive file is fully written
        for number in range(1, until):
            raw = st.get(T_NUM2TXS, _be8(number))
            if raw is None:
                continue
            for h in Reader(raw).seq(lambda r: r.blob()):
                st.remove(T_TX, h)
                st.remove(T_RECEIPT, h)
            st.remove(T_NUM2TXS, _be8(number))
            st.remove(T_NONCES, _be8(number))
        st.compact()
        print(json.dumps({"archived_blocks": n_blocks,
                          "records": n_records, "file": out}))
    finally:
        st.close()


def restore(path: str, archive_file: str) -> None:
    st = WalStorage(path)
    try:
        n = 0
        for table, key, value in _read_records(archive_file):
            st.set(table, key, value)
            n += 1
        print(json.dumps({"restored_records": n}))
    finally:
        st.close()


def info(archive_file: str) -> None:
    counts: dict[str, int] = {}
    for table, _k, _v in _read_records(archive_file):
        counts[table] = counts.get(table, 0) + 1
    print(json.dumps(counts, indent=1))


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    a = sub.add_parser("archive")
    a.add_argument("path")
    a.add_argument("archive_file")
    a.add_argument("--until", type=int, required=True)
    r = sub.add_parser("restore")
    r.add_argument("path")
    r.add_argument("archive_file")
    i = sub.add_parser("info")
    i.add_argument("archive_file")
    args = ap.parse_args()
    if args.cmd == "archive":
        archive(args.path, args.archive_file, args.until)
    elif args.cmd == "restore":
        restore(args.path, args.archive_file)
    else:
        info(args.archive_file)


if __name__ == "__main__":
    main()
