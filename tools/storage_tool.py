#!/usr/bin/env python3
"""storage-tool — inspect and repair a node's storage offline.

Reference counterpart: /root/reference/tools/storage-tool (RocksDB
inspection utility). Operates on a stopped node's WAL storage directory.

Commands:
  stats  <path>                      table/row/byte counts
  tables <path>                      list tables
  scan   <path> <table> [prefix-hex] list keys (values with --values)
  get    <path> <table> <key-hex>    print one value (hex)
  set    <path> <table> <key-hex> <value-hex>   write one value (repair)
  remove <path> <table> <key-hex>    delete one key
  compact <path>                     rewrite snapshot, truncate the WAL
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fisco_bcos_tpu.storage.wal import WalStorage  # noqa: E402


def _open(path: str) -> WalStorage:
    if not os.path.isdir(path):
        raise SystemExit(f"no storage directory at {path}")
    return WalStorage(path)


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, extra in (
            ("stats", []), ("tables", []), ("compact", []),
            ("scan", ["table", ["prefix", "?"]]),
            ("get", ["table", "key"]),
            ("set", ["table", "key", "value"]),
            ("remove", ["table", "key"])):
        p = sub.add_parser(name)
        p.add_argument("path")
        for arg in extra:
            if isinstance(arg, list):
                p.add_argument(arg[0], nargs="?", default="")
            else:
                p.add_argument(arg)
        if name == "scan":
            p.add_argument("--values", action="store_true")
    args = ap.parse_args()
    st = _open(args.path)
    try:
        if args.cmd == "tables":
            print(json.dumps(sorted(st._tables)))
        elif args.cmd == "stats":
            out = {t: {"rows": len(rows),
                       "bytes": sum(len(k) + len(v)
                                    for k, v in rows.items())}
                   for t, rows in sorted(st._tables.items())}
            print(json.dumps(out, indent=1))
        elif args.cmd == "scan":
            prefix = bytes.fromhex(args.prefix) if args.prefix else b""
            for k in st.keys(args.table, prefix):
                if args.values:
                    print(k.hex(), (st.get(args.table, k) or b"").hex())
                else:
                    print(k.hex())
        elif args.cmd == "get":
            v = st.get(args.table, bytes.fromhex(args.key))
            if v is None:
                raise SystemExit("no such key")
            print(v.hex())
        elif args.cmd == "set":
            st.set(args.table, bytes.fromhex(args.key),
                   bytes.fromhex(args.value))
            print("ok")
        elif args.cmd == "remove":
            st.remove(args.table, bytes.fromhex(args.key))
            print("ok")
        elif args.cmd == "compact":
            st.compact()
            print("ok")
    finally:
        st.close()


if __name__ == "__main__":
    main()
