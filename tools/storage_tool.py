#!/usr/bin/env python3
"""storage-tool — inspect and repair a node's storage offline.

Reference counterpart: /root/reference/tools/storage-tool (RocksDB
inspection utility). Operates on a stopped node's storage directory —
WAL-backed or the disk engine (auto-detected by its CURRENT manifest
pointer; `stats` then also reports segments/memtable/bloom counters).

Commands:
  stats  <path>                      table/row/byte counts; for the disk
                                     engine also per-level segment/byte/
                                     debt stats (leveled compaction)
  tables <path>                      list tables
  scan   <path> <table> [prefix-hex] list keys (values with --values)
  get    <path> <table> <key-hex>    print one value (hex)
  set    <path> <table> <key-hex> <value-hex>   write one value (repair)
  remove <path> <table> <key-hex>    delete one key
  compact <path>                     offline catch-up: drain ALL
                                     compaction debt (leveled engine —
                                     e.g. after a long outage left the
                                     node behind), or rewrite snapshot +
                                     truncate WAL (wal backend)

Disk-engine directories written with `key_page_size` (the default) are
detected by their `_kp_/meta` rows and read through the page layer, so
scan/get/set/remove operate on LOGICAL rows; stats reports both the page
layer and the underlying engine (levels, debt, bloom counters).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fisco_bcos_tpu.storage.wal import WalStorage  # noqa: E402


def _open(path: str):
    """`path` is a WAL directory, OR a max_cluster.json whose live shard
    services the tool inspects through the sharded coordinator (Max-mode
    deployments have no single on-disk directory to open)."""
    if os.path.isfile(path) and path.endswith(".json"):
        from fisco_bcos_tpu.storage.sharded import (
            ShardedStorage, make_shard_client)

        with open(path) as f:
            cluster = json.load(f)
        return ShardedStorage(
            [make_shard_client(s["host"], s["port"])
             for s in cluster["shards"]], recover=False)
    if not os.path.isdir(path):
        raise SystemExit(f"no storage directory at {path}")
    # disk-engine layout: CURRENT manifest pointer, or (before the first
    # flush ever wrote a manifest) rotated wal-*.log / seg-*.sst files —
    # opening those as WalStorage would report an empty store
    names = os.listdir(path)
    if "CURRENT" in names or any(
            (n.startswith("wal-") and n.endswith(".log"))
            or (n.startswith("seg-") and n.endswith(".sst"))
            for n in names):
        from fisco_bcos_tpu.storage.engine import DiskStorage
        from fisco_bcos_tpu.storage.keypage import META_KEY, KeyPageStorage

        st = DiskStorage(path, auto_compact=False)
        # page-packed layout (key_page_size, on by default for disk):
        # wrap so the operator addresses logical rows, not raw pages
        if any(st.get(t, META_KEY) is not None for t in st.tables()):
            return KeyPageStorage(st)
        return st
    return WalStorage(path)


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, extra in (
            ("stats", []), ("tables", []), ("compact", []),
            ("scan", ["table", ["prefix", "?"]]),
            ("get", ["table", "key"]),
            ("set", ["table", "key", "value"]),
            ("remove", ["table", "key"])):
        p = sub.add_parser(name)
        p.add_argument("path")
        for arg in extra:
            if isinstance(arg, list):
                p.add_argument(arg[0], nargs="?", default="")
            else:
                p.add_argument(arg)
        if name == "scan":
            p.add_argument("--values", action="store_true")
    args = ap.parse_args()
    st = _open(args.path)
    try:
        if args.cmd == "tables":
            print(json.dumps(st.tables()))
        elif args.cmd == "stats":
            out = {}
            for t in st.tables():
                ks = list(st.keys(t))
                vs = st.get_batch(t, ks)  # batched: one RPC per shard
                out[t] = {"rows": len(ks),
                          "bytes": sum(len(k) + len(v or b"")
                                       for k, v in zip(ks, vs))}
            engine_stats = getattr(st, "stats", None)
            if engine_stats is not None:
                out["_engine"] = engine_stats()
            print(json.dumps(out, indent=1))
        elif args.cmd == "scan":
            prefix = bytes.fromhex(args.prefix) if args.prefix else b""
            ks = list(st.keys(args.table, prefix))
            if args.values:
                for k, v in zip(ks, st.get_batch(args.table, ks)):
                    print(k.hex(), (v or b"").hex())
            else:
                for k in ks:
                    print(k.hex())
        elif args.cmd == "get":
            v = st.get(args.table, bytes.fromhex(args.key))
            if v is None:
                raise SystemExit("no such key")
            print(v.hex())
        elif args.cmd == "set":
            st.set(args.table, bytes.fromhex(args.key),
                   bytes.fromhex(args.value))
            print("ok")
        elif args.cmd == "remove":
            st.remove(args.table, bytes.fromhex(args.key))
            print("ok")
        elif args.cmd == "compact":
            if not hasattr(st, "compact"):
                raise SystemExit("compact: local WAL storage only")
            debt_fn = getattr(st, "compaction_debt_bytes", None)
            before = debt_fn() if debt_fn is not None else None
            st.compact()
            if debt_fn is not None:
                print(json.dumps({"debt_bytes_before": before,
                                  "debt_bytes_after": debt_fn()}))
            print("ok")
    finally:
        st.close()


if __name__ == "__main__":
    main()
