#!/usr/bin/env python3
"""light-monitor — liveness probe for running nodes.

Reference counterpart: /root/reference/tools/BcosAirBuilder/light_monitor.sh
(curl-based JSON-RPC probes with alarm hooks). Checks each endpoint's
blockNumber/syncStatus/consensus view, flags nodes that fall behind the
majority head or stop advancing, and exits non-zero if any check fails —
cron/systemd-timer friendly.

Usage: python tools/light_monitor.py http://127.0.0.1:8545 [...more]
       [--lag 5] [--json] [--group group0]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def rpc(url: str, method: str, params: list, timeout: float = 5.0):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(url, data=body,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read())
    if "error" in out:
        raise RuntimeError(out["error"].get("message", "rpc error"))
    return out.get("result")


def probe(url: str, group: str) -> dict:
    try:
        number = rpc(url, "getBlockNumber", [group, ""])
        sync = rpc(url, "getSyncStatus", [group, ""])
        pending = rpc(url, "getPendingTxSize", [group, ""])
        return {"url": url, "ok": True, "blockNumber": int(number),
                "pendingTx": int(pending),
                "peers": len(sync.get("peers", []))
                if isinstance(sync, dict) else 0}
    except Exception as exc:  # noqa: BLE001 — operator-facing diagnostics
        return {"url": url, "ok": False, "error": str(exc)[:200]}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("urls", nargs="+")
    ap.add_argument("--lag", type=int, default=5,
                    help="max blocks a node may trail the highest head")
    ap.add_argument("--group", default="group0")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    results = [probe(u, args.group) for u in args.urls]
    heads = [r["blockNumber"] for r in results if r.get("ok")]
    head = max(heads) if heads else 0
    failed = False
    for r in results:
        if not r["ok"]:
            failed = True
            r["alarm"] = "unreachable"
        elif head - r["blockNumber"] > args.lag:
            failed = True
            r["alarm"] = f"lagging {head - r['blockNumber']} blocks"
    if args.json:
        print(json.dumps({"head": head, "nodes": results}, indent=1))
    else:
        for r in results:
            status = r.get("alarm", "ok" if r["ok"] else "down")
            print(f"{r['url']}: {status} "
                  f"(height={r.get('blockNumber', '-')}, "
                  f"pending={r.get('pendingTx', '-')})")
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
