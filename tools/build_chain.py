#!/usr/bin/env python3
"""build_chain — generate an N-node chain deployment directory.

Counterpart of the reference's tools/BcosAirBuilder/build_chain.sh (generate
an N-node Air chain: keys, per-node config.ini, shared genesis) and the
BcosBuilder Pro/Max deployers. Output layout:

    <out>/
      node0/ config.ini  genesis  node.key[.enc]
      node1/ ...
      chain_info.json          (node ids + rpc ports, for operators/SDKs)

Usage:
    python tools/build_chain.py -n 4 -o /tmp/mychain [--sm] \
        [--consensus pbft] [--rpc-base-port 20200] [--encrypt-key PASS]

Boot a generated node in-process:
    from fisco_bcos_tpu.tool import load_node
    node = load_node("/tmp/mychain/node0", gateway=...)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fisco_bcos_tpu.crypto.suite import make_suite  # noqa: E402
from fisco_bcos_tpu.init.node import NodeConfig  # noqa: E402
from fisco_bcos_tpu.tool.config import ChainConfig, save_node_config  # noqa: E402


def _write_monitor_stack(out_dir: str, targets: list[str]) -> None:
    """Copy the monitor bundle (tools/monitor) into the chain dir with the
    Prometheus target list rewritten to the generated nodes' ports."""
    import shutil

    src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "monitor")
    dst = os.path.join(out_dir, "monitor")
    shutil.copytree(src, dst, dirs_exist_ok=True)
    lines = ["global:", "  scrape_interval: 5s", "", "scrape_configs:",
             "  - job_name: fisco-bcos-tpu", "    static_configs:",
             "      - targets:"]
    lines += [f'          - "{t}"' for t in targets]
    with open(os.path.join(dst, "prometheus.yml"), "w") as f:
        f.write("\n".join(lines) + "\n")


def build_chain(out_dir: str, n_nodes: int, sm_crypto: bool = False,
                consensus: str = "pbft", chain_id: str = "chain0",
                group_id: str = "group0", rpc_base_port: int | None = None,
                encrypt_passphrase: bytes | None = None,
                crypto_backend: str = "auto",
                storage_backend: str = "auto",
                metrics_base_port: int | None = None,
                sm_tls: bool = False,
                p2p_base_port: int | None = None,
                p2p_ports: list[int] | None = None,
                host: str = "127.0.0.1") -> dict:
    suite = make_suite(sm_crypto, backend="host")
    keypairs = [suite.generate_keypair() for _ in range(n_nodes)]
    chain = ChainConfig(chain_id=chain_id, group_id=group_id,
                        sm_crypto=sm_crypto, consensus_type=consensus,
                        sealers=[kp.pub_bytes for kp in keypairs])
    ca = None
    if sm_tls:
        from fisco_bcos_tpu.net.smtls import CertificateAuthority
        from fisco_bcos_tpu.tool.config import save_smtls_files
        ca = CertificateAuthority(name=f"{chain_id}-ca")
    info = {"chain_id": chain_id, "group_id": group_id,
            "sm_crypto": sm_crypto, "sm_tls": sm_tls,
            "consensus": consensus, "nodes": []}
    # p2p plane: each node listens on its port and is configured with every
    # OTHER node's endpoint (the deterministic smaller-id-dials rule in
    # net/p2p.py picks the single live session per pair)
    if p2p_ports is None and p2p_base_port is not None:
        p2p_ports = [p2p_base_port + i for i in range(n_nodes)]
    metric_targets = []
    for i, kp in enumerate(keypairs):
        node_dir = os.path.join(out_dir, f"node{i}")
        cfg = NodeConfig(
            chain_id=chain_id, group_id=group_id, sm_crypto=sm_crypto,
            storage_path="data", consensus=consensus,
            storage_backend=storage_backend,
            crypto_backend=crypto_backend,
            rpc_port=(rpc_base_port + i) if rpc_base_port is not None else None,
            metrics_port=(metrics_base_port + i)
            if metrics_base_port is not None else None,
            p2p_host=host,
            p2p_port=p2p_ports[i] if p2p_ports else None,
            p2p_peers=[(host, p) for j, p in enumerate(p2p_ports or [])
                       if j != i],
        )
        save_node_config(node_dir, cfg, chain, kp.secret,
                         storage_passphrase=encrypt_passphrase)
        if ca is not None:
            save_smtls_files(node_dir, ca.pub, ca.issue(f"node{i}"),
                             storage_passphrase=encrypt_passphrase)
        if cfg.metrics_port is not None:
            metric_targets.append(f"127.0.0.1:{cfg.metrics_port}")
        info["nodes"].append({
            "dir": node_dir,
            "node_id": kp.pub_bytes.hex(),
            "rpc_port": cfg.rpc_port,
            "metrics_port": cfg.metrics_port,
            "p2p_port": cfg.p2p_port,
        })
    if metric_targets:
        _write_monitor_stack(out_dir, metric_targets)
    with open(os.path.join(out_dir, "chain_info.json"), "w") as f:
        json.dump(info, f, indent=2)
    return info


def build_max_cluster(out_dir: str, n_shards: int = 3,
                      n_registries: int = 3,
                      shard_base_port: int = 21100,
                      registry_base_port: int = 21200,
                      host: str = "127.0.0.1") -> dict:
    """Generate the Max-mode shared-services layout: a sharded storage
    cluster + lease registries (the TiKV + etcd plane). Boot each member
    with fisco_bcos_tpu.services.max_node.start_storage_shard /
    start_lease_registry, and node replicas with MaxNode against
    max_cluster.json's endpoints."""
    shards, registries = [], []
    for i in range(n_shards):
        d = os.path.join(out_dir, "shards", f"shard{i}")
        os.makedirs(d, exist_ok=True)
        shards.append({"dir": d, "host": host,
                       "port": shard_base_port + i})
    regs_dir = os.path.join(out_dir, "registries")
    os.makedirs(regs_dir, exist_ok=True)
    for i in range(n_registries):
        registries.append({"state": os.path.join(regs_dir, f"reg{i}.json"),
                           "host": host, "port": registry_base_port + i})
    cluster = {"shards": shards, "registries": registries}
    with open(os.path.join(out_dir, "max_cluster.json"), "w") as f:
        json.dump(cluster, f, indent=2)
    return cluster


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--nodes", type=int, default=4)
    ap.add_argument("-o", "--output", required=True)
    ap.add_argument("--sm", action="store_true", help="SM2/SM3 chain")
    ap.add_argument("--consensus", default="pbft", choices=["pbft", "solo"])
    ap.add_argument("--chain-id", default="chain0")
    ap.add_argument("--group-id", default="group0")
    ap.add_argument("--rpc-base-port", type=int, default=None)
    ap.add_argument("--p2p-base-port", type=int, default=None,
                    help="per-node TCP p2p listeners + full-mesh peer "
                         "lists (required to run nodes as OS processes)")
    ap.add_argument("--metrics-base-port", type=int, default=None,
                    help="per-node Prometheus ports + monitor stack bundle")
    ap.add_argument("--sm-tls", action="store_true",
                    help="issue dual-cert SM-TLS credentials per node")
    ap.add_argument("--storage", default="auto",
                    choices=["auto", "memory", "wal", "disk"],
                    help="[storage] backend: auto = WAL-backed; disk = "
                         "log-structured engine (restart flat in chain "
                         "length, datasets beyond RAM)")
    ap.add_argument("--encrypt-key", default=None,
                    help="passphrase to encrypt node keys at rest")
    ap.add_argument("--mode", default="air", choices=["air", "max"],
                    help="max adds the shared shard cluster + lease "
                         "registries layout (max_cluster.json)")
    ap.add_argument("--shards", type=int, default=3)
    ap.add_argument("--registries", type=int, default=3)
    args = ap.parse_args()
    info = build_chain(
        args.output, args.nodes, sm_crypto=args.sm,
        consensus=args.consensus, chain_id=args.chain_id,
        group_id=args.group_id, rpc_base_port=args.rpc_base_port,
        p2p_base_port=args.p2p_base_port,
        metrics_base_port=args.metrics_base_port, sm_tls=args.sm_tls,
        storage_backend=args.storage,
        encrypt_passphrase=args.encrypt_key.encode() if args.encrypt_key else None)
    if args.mode == "max":
        info["max_cluster"] = build_max_cluster(
            args.output, n_shards=args.shards,
            n_registries=args.registries)
    print(json.dumps(info, indent=2))


if __name__ == "__main__":
    main()
