#!/usr/bin/env python3
"""bcoslint — repo-specific concurrency/hygiene invariants as AST passes.

The static half of the concurrency-correctness plane (the runtime half is
fisco_bcos_tpu/analysis/lockcheck.py): every rule encodes an invariant a
past PR's review wave had to find by hand. Gating CI (tools/sanitize_ci.sh
--lint) against the committed baseline keeps the repo at zero NEW
violations while grandfathered ones carry a written justification.

Usage:
    python tools/bcoslint.py                    # lint default paths vs baseline
    python tools/bcoslint.py --list-rules
    python tools/bcoslint.py --no-baseline      # show EVERY violation
    python tools/bcoslint.py --update-baseline  # rewrite the baseline file
    python tools/bcoslint.py path.py ...        # explicit files/dirs

Suppression (same line or the line directly above):
    something_flagged()  # bcoslint: disable=wallclock-deadline
    # bcoslint: disable=all

Baseline file format (tools/bcoslint_baseline.txt), one entry per line:
    rule|path|scope|fingerprint|justification
`scope` is the enclosing qualname; `fingerprint` is the offending source
line with whitespace collapsed — entries survive line-number churn. A
violation matching (rule, path, scope, fingerprint) is grandfathered;
stale entries are reported as warnings and pruned by --update-baseline.

Rules:
    raw-lock              threading.Lock/RLock/Condition() constructed in a
                          hot module instead of the lockcheck factories
    lock-order            lexically nested `with` over canonical locks in
                          rank-inverting order (analysis/lockorder.RANK)
    blocking-under-lock   fsync / socket send / suite batch / subprocess /
                          sleep lexically inside a `with` over a HOT lock
                          whose allow-set excludes that kind
    bare-except           `except:` catches SystemExit/KeyboardInterrupt too
    swallowed-worker-exception
                          an except handler that is only pass/continue
                          inside a worker run()/_loop() — silent thread
                          death (how the lane dispatcher died in PR 11)
    wallclock-deadline    time.time() compared or added/subtracted — wall
                          clock steps under NTP; deadlines/elapsed need
                          time.monotonic()
    fsync-no-failpoint    a storage/snapshot function performs fsync or
                          os.replace but crosses no failpoint site — the
                          kill -9 matrix cannot reach the new edge
    metrics-cardinality   a metrics label value built from .hex()/f-string/
                          str() — unbounded label sets explode Prometheus
                          series
    mutable-default       def f(x=[]) / {} / set() — shared across calls
    dict-iter-mutation    `for k in d:` whose body pops/clears d — dict
                          mutated during iteration raises at runtime
    unused-import         import never referenced (hygiene pass)
    thread-start-in-ctor  a thread started inside __init__ — the new thread
                          can observe a partially-constructed object (the
                          p2p _Session writer raced its own registration)
    log-in-hot-loop       f-string log call inside a loop on the hot path —
                          formats per item even when the level is disabled
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Iterator, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = ("fisco_bcos_tpu", "tools", "benchmark")
DEFAULT_BASELINE = os.path.join(REPO, "tools", "bcoslint_baseline.txt")

# analysis/lockorder.py loaded by path: the package __init__ imports jax,
# which a lint pass must never pay for (or require)
_spec = importlib.util.spec_from_file_location(
    "_bcoslint_lockorder",
    os.path.join(REPO, "fisco_bcos_tpu", "analysis", "lockorder.py"))
lockorder = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lockorder)

SUPPRESS_RE = re.compile(r"#\s*bcoslint:\s*disable=([a-z\-,\s]+|all)")

# files exempt from raw-lock (the checker itself builds the primitives)
RAW_LOCK_EXEMPT = ("analysis/lockcheck.py",)

# directories where every fsync/atomic-rename edge must be failpoint-armed
FSYNC_FP_SCOPE = ("fisco_bcos_tpu/storage/", "fisco_bcos_tpu/snapshot/")

WORKER_FN_RE = re.compile(r"^(_?run\w*|.*_loop|execute_worker|_recv\w*)$")

BLOCKING_ATTRS = {
    "fsync": "fsync", "fdatasync": "fsync",
    "sendall": "socket_send", "send_text": "socket_send",
    "send_binary": "socket_send",
    "verify_batch": "suite_batch", "recover_batch": "suite_batch",
    "hash_batch": "suite_batch",
}
SUBPROCESS_ATTRS = {"run", "check_call", "check_output", "call", "Popen"}


@dataclass
class Violation:
    rule: str
    path: str        # repo-relative
    line: int
    scope: str
    text: str        # raw source line (stripped)
    message: str

    @property
    def fingerprint(self) -> str:
        return " ".join(self.text.split())

    @property
    def key(self) -> tuple:
        return (self.rule, self.path, self.scope, self.fingerprint)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}\n"
                f"    {self.text.strip()}  (scope: {self.scope})")


@dataclass
class FileCtx:
    path: str              # absolute
    relpath: str           # repo-relative, /-separated
    src: str
    lines: list[str]
    tree: ast.Module
    scopes: dict[int, str] = field(default_factory=dict)  # id(node)->qualname
    lock_attrs: dict[str, str] = field(default_factory=dict)

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(id(node), "<module>")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        for ln in (lineno, lineno - 1):
            m = SUPPRESS_RE.search(self.line_text(ln))
            if m:
                rules = m.group(1).strip()
                if rules == "all" or rule in [r.strip()
                                              for r in rules.split(",")]:
                    return True
        return False


def _build_scopes(ctx: FileCtx) -> None:
    def walk(node: ast.AST, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual != "<module>" \
                    else child.name
            ctx.scopes[id(child)] = q
            walk(child, q)
    ctx.scopes[id(ctx.tree)] = "<module>"
    walk(ctx.tree, "<module>")


def _make_ctx(src: str, path: str, rel: str) -> FileCtx:
    ctx = FileCtx(path=path, relpath=rel, src=src,
                  lines=src.splitlines(), tree=ast.parse(src, filename=path))
    _build_scopes(ctx)
    for suffix, attrs in lockorder.MODULE_LOCK_ATTRS.items():
        if rel.endswith(suffix):
            ctx.lock_attrs = attrs
            break
    return ctx


def load_file(path: str) -> Optional[FileCtx]:
    rel = os.path.relpath(os.path.abspath(path), REPO).replace(os.sep, "/")
    try:
        src = open(path, encoding="utf-8").read()
        return _make_ctx(src, path, rel)
    except (OSError, SyntaxError) as exc:
        print(f"bcoslint: cannot parse {rel}: {exc}", file=sys.stderr)
        return None


def lint_source(src: str, relpath: str) -> list[Violation]:
    """Lint a source STRING as if it lived at repo-relative `relpath`
    (path-scoped rules key off it). The test suite's entry point."""
    ctx = _make_ctx(src, relpath, relpath)
    out: list[Violation] = []
    for fn in RULES.values():
        out.extend(fn(ctx))
    return out


def _v(ctx: FileCtx, rule: str, node: ast.AST, message: str
       ) -> Optional[Violation]:
    line = getattr(node, "lineno", 1)
    if ctx.suppressed(line, rule):
        return None
    return Violation(rule=rule, path=ctx.relpath, line=line,
                     scope=ctx.scope_of(node),
                     text=ctx.line_text(line).strip(), message=message)


# -- rule: raw-lock --------------------------------------------------------

def rule_raw_lock(ctx: FileCtx) -> Iterator[Violation]:
    if not ctx.lock_attrs or any(ctx.relpath.endswith(e)
                                 for e in RAW_LOCK_EXEMPT):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("Lock", "RLock", "Condition") and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "threading":
            v = _v(ctx, "raw-lock", node,
                   f"threading.{node.func.attr}() in a hot module — use "
                   "analysis.lockcheck.make_lock/make_rlock/make_condition")
            if v:
                yield v


# -- rules: lock-order + blocking-under-lock (shared with-stack walk) ------

def _lock_name_of(ctx: FileCtx, expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return ctx.lock_attrs.get(expr.attr)
    if isinstance(expr, ast.Attribute):  # e.g. task.lock
        return ctx.lock_attrs.get(expr.attr)
    return None


def _blocking_kind(node: ast.Call) -> Optional[tuple[str, str]]:
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return None
    kind = BLOCKING_ATTRS.get(fn.attr)
    if kind:
        return kind, fn.attr
    root = fn.value
    if isinstance(root, ast.Name):
        if root.id == "time" and fn.attr == "sleep":
            return "sleep", "time.sleep"
        if root.id == "subprocess" and fn.attr in SUBPROCESS_ATTRS:
            return "subprocess", f"subprocess.{fn.attr}"
        if root.id == "os" and fn.attr == "replace":
            return "fsync", "os.replace"
    return None


def rule_with_locks(ctx: FileCtx) -> Iterator[Violation]:
    if not ctx.lock_attrs:
        return
    out: list[Violation] = []

    def walk(node: ast.AST, stack: tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            entered = list(stack)
            for item in node.items:
                name = _lock_name_of(ctx, item.context_expr)
                if name is None:
                    continue
                for held in entered:
                    ra = lockorder.RANK.get(held)
                    rb = lockorder.RANK.get(name)
                    if held != name and ra is not None and rb is not None \
                            and ra >= rb:
                        v = _v(ctx, "lock-order", node,
                               f"acquires {name} (rank {rb}) while "
                               f"holding {held} (rank {ra}) — canonical "
                               "order is outer-before-inner "
                               "(analysis/lockorder.py)")
                        if v:
                            out.append(v)
                entered.append(name)
            for child in node.body:
                walk(child, tuple(entered))
            return
        if isinstance(node, ast.Call) and stack:
            bk = _blocking_kind(node)
            if bk is not None:
                kind, what = bk
                for held in stack:
                    allow = lockorder.HOT_LOCKS.get(held)
                    if allow is not None and kind not in allow:
                        v = _v(ctx, "blocking-under-lock", node,
                               f"{what} ({kind}) inside `with` over hot "
                               f"lock {held} — move the blocking work "
                               "outside the lock")
                        if v:
                            out.append(v)
        # nested defs start with an EMPTY stack: the closure runs later,
        # not under the lexically enclosing with
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                walk(child, ())
            return
        for child in ast.iter_child_nodes(node):
            walk(child, stack)

    walk(ctx.tree, ())
    yield from out


# -- rule: bare-except -----------------------------------------------------

def rule_bare_except(ctx: FileCtx) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            v = _v(ctx, "bare-except", node,
                   "bare `except:` also catches SystemExit/"
                   "KeyboardInterrupt — name the exception class")
            if v:
                yield v


# -- rule: swallowed-worker-exception --------------------------------------

def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in handler.body)


def rule_swallowed_worker(ctx: FileCtx) -> Iterator[Violation]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not WORKER_FN_RE.match(fn.name):
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, ast.While):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.ExceptHandler) and \
                        _handler_swallows(node):
                    v = _v(ctx, "swallowed-worker-exception", node,
                           f"exception swallowed (pass/continue) inside "
                           f"worker loop {fn.name}() — a dying handler "
                           "is invisible; log it (LOG.exception)")
                    if v:
                        yield v


# -- rule: wallclock-deadline ----------------------------------------------

def _is_time_time(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time")


def rule_wallclock(ctx: FileCtx) -> Iterator[Violation]:
    flagged: set[int] = set()
    for node in ast.walk(ctx.tree):
        hit = None
        if isinstance(node, ast.Compare):
            ops = [node.left] + list(node.comparators)
            if any(_is_time_time(o) for o in ops):
                hit = node
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Add, ast.Sub)):
            if _is_time_time(node.left) or _is_time_time(node.right):
                hit = node
        if hit is not None and hit.lineno not in flagged:
            flagged.add(hit.lineno)
            v = _v(ctx, "wallclock-deadline", hit,
                   "time.time() used for a deadline/elapsed computation — "
                   "wall clock steps under NTP; use time.monotonic() "
                   "(wall-clock timestamps for wire/display are fine)")
            if v:
                yield v


# -- rule: fsync-no-failpoint ----------------------------------------------

def _has_failpoint_ref(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("fire",
                                                           "fire_lossy"):
                return True
            if isinstance(f, ast.Attribute) and f.attr == "_maybe_fail":
                return True
    return False


def rule_fsync_failpoint(ctx: FileCtx) -> Iterator[Violation]:
    if not any(ctx.relpath.startswith(p) for p in FSYNC_FP_SCOPE):
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_edge = False
        edge_node = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "os" and \
                    node.func.attr in ("fsync", "fdatasync", "replace"):
                has_edge = True
                edge_node = node
                break
        if has_edge and not _has_failpoint_ref(fn):
            v = _v(ctx, "fsync-no-failpoint", edge_node,
                   f"{fn.name}() crosses a durability edge "
                   "(fsync/atomic rename) with no failpoint site — the "
                   "kill -9 matrix cannot exercise it "
                   "(utils/failpoints.py)")
            if v:
                yield v


# -- rule: metrics-cardinality ---------------------------------------------

def _label_value_hazard(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.JoinedStr):
        return "f-string"
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr == "hex":
            return ".hex()"
        if isinstance(f, ast.Name) and f.id in ("str", "repr", "hex"):
            return f"{f.id}()"
    return None


def rule_metrics_cardinality(ctx: FileCtx) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("inc", "set_gauge", "observe")):
            continue
        labels = None
        for kw in node.keywords:
            if kw.arg == "labels":
                labels = kw.value
        if labels is None and len(node.args) >= 3:
            labels = node.args[2]
        if not isinstance(labels, ast.Dict):
            continue
        for k, val in zip(labels.keys, labels.values):
            hazard = _label_value_hazard(val)
            if hazard:
                kn = getattr(k, "value", "?")
                v = _v(ctx, "metrics-cardinality", node,
                       f"label {kn!r} built from {hazard} — unbounded "
                       "values explode Prometheus series; use a bounded "
                       "enum or drop the label")
                if v:
                    yield v
                break


# -- rule: mutable-default -------------------------------------------------

def rule_mutable_default(ctx: FileCtx) -> Iterator[Violation]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for d in list(fn.args.defaults) + list(fn.args.kw_defaults):
            if d is None:
                continue
            bad = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set"))
            if bad:
                v = _v(ctx, "mutable-default", d,
                       f"mutable default argument in {fn.name}() is "
                       "shared across calls — default to None")
                if v:
                    yield v


# -- rule: dict-iter-mutation ----------------------------------------------

def rule_dict_iter_mutation(ctx: FileCtx) -> Iterator[Violation]:
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, ast.For) or not isinstance(loop.iter,
                                                           ast.Name):
            continue
        target = loop.iter.id
        for node in ast.walk(loop):
            mutates = False
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("pop", "popitem", "clear") and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == target:
                mutates = True
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == target:
                        mutates = True
            if mutates:
                v = _v(ctx, "dict-iter-mutation", node,
                       f"`{target}` mutated while `for ... in {target}:` "
                       "iterates it — materialise the keys first "
                       "(`for k in list(d):`)")
                if v:
                    yield v
                break


# -- rule: unused-import ---------------------------------------------------

def rule_unused_import(ctx: FileCtx) -> Iterator[Violation]:
    if ctx.relpath.endswith("__init__.py"):
        return  # re-export surface: bindings ARE the API
    # class-scope imports bind CLASS ATTRIBUTES (referenced as self.X /
    # cls.X) — usage is attribute access the Name scan below cannot see,
    # so they are exempt
    class_scope: set[int] = set()
    for cls in ast.walk(ctx.tree):
        if isinstance(cls, ast.ClassDef):
            for stmt in cls.body:
                if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    class_scope.add(id(stmt))
    bound: dict[str, ast.stmt] = {}
    for node in ast.walk(ctx.tree):
        if id(node) in class_scope:
            continue
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                bound[name] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                if a.asname == a.name:
                    continue  # explicit re-export convention
                bound[a.asname or a.name] = node
    if not bound:
        return
    used: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    # names exported via __all__ count as used
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            used.add(el.value)
    for name, node in sorted(bound.items()):
        if name not in used:
            v = _v(ctx, "unused-import", node,
                   f"import {name!r} is never used")
            if v:
                yield v


# -- rule: thread-start-in-ctor --------------------------------------------

def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread" and \
            isinstance(fn.value, ast.Name) and fn.value.id == "threading":
        return True
    if isinstance(fn, ast.Name) and (fn.id == "Thread"
                                     or fn.id.endswith("Thread")):
        return True
    return False


def rule_thread_start_in_ctor(ctx: FileCtx) -> Iterator[Violation]:
    """A thread started inside __init__ can observe the object before the
    ctor finished assigning its fields (the p2p _Session writer raced its
    own session registration this way). Expose start() and have the owner
    call it after construction completes."""
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        threadlike = any(
            (isinstance(b, ast.Name)
             and (b.id in ("Thread", "Worker") or b.id.endswith("Thread")))
            or (isinstance(b, ast.Attribute) and b.attr == "Thread")
            for b in cls.bases)
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) or fn.name != "__init__":
                continue
            # self-attrs / locals assigned a Thread in THIS ctor
            thread_names: set[tuple[str, str]] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _is_thread_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            thread_names.add(("self", t.attr))
                        elif isinstance(t, ast.Name):
                            thread_names.add(("local", t.id))
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "start"):
                    continue
                recv = node.func.value
                hit = (
                    # Thread(...).start() inline
                    (isinstance(recv, ast.Call) and _is_thread_ctor(recv))
                    # self._t = Thread(...); ... self._t.start()
                    or (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and ("self", recv.attr) in thread_names)
                    # t = Thread(...); t.start()
                    or (isinstance(recv, ast.Name)
                        and ("local", recv.id) in thread_names)
                    # self.start() in a Thread/Worker subclass ctor
                    or (isinstance(recv, ast.Name) and recv.id == "self"
                        and threadlike))
                if hit:
                    v = _v(ctx, "thread-start-in-ctor", node,
                           f"thread started inside {cls.name}.__init__ — "
                           "the new thread can see a partially-constructed "
                           "object; expose start() and call it after "
                           "construction")
                    if v:
                        yield v


# -- rule: log-in-hot-loop -------------------------------------------------

# modules on the wire->lane->seal hot path: a per-item f-string log call
# formats (and allocates) even when the level is disabled
HOT_LOG_SCOPE = ("fisco_bcos_tpu/txpool/", "fisco_bcos_tpu/crypto/",
                 "fisco_bcos_tpu/protocol/", "fisco_bcos_tpu/sealer/")
LOG_RECEIVERS = ("LOG", "log", "logger", "_LOG")
LOG_LEVELS = ("debug", "info", "warning", "error", "exception", "critical")


def rule_log_in_hot_loop(ctx: FileCtx) -> Iterator[Violation]:
    if not ctx.relpath.startswith(HOT_LOG_SCOPE):
        return
    out: list[Violation] = []

    def eager(arg: ast.expr) -> bool:
        if isinstance(arg, ast.JoinedStr):
            return True  # f-string: formatted before the level check
        return (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "format"
                and isinstance(arg.func.value, ast.Constant))

    def walk(node: ast.AST, loop: int) -> None:
        for child in ast.iter_child_nodes(node):
            depth = loop
            if isinstance(child, (ast.For, ast.While)):
                depth += 1
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                depth = 0  # closure body runs on its own schedule
            if depth > 0 and isinstance(child, ast.Call) and \
                    isinstance(child.func, ast.Attribute) and \
                    child.func.attr in LOG_LEVELS and \
                    isinstance(child.func.value, ast.Name) and \
                    child.func.value.id in LOG_RECEIVERS and \
                    any(eager(a) for a in child.args):
                v = _v(ctx, "log-in-hot-loop", child,
                       "f-string log call inside a hot-path loop formats "
                       "per item even when the level is off — hoist it out "
                       "of the loop or use lazy %-style args")
                if v:
                    out.append(v)
            walk(child, depth)

    walk(ctx.tree, 0)
    yield from out


RULES = {
    "raw-lock": rule_raw_lock,
    "lock-order": rule_with_locks,       # emits lock-order AND
    #                                      blocking-under-lock violations
    "bare-except": rule_bare_except,
    "swallowed-worker-exception": rule_swallowed_worker,
    "wallclock-deadline": rule_wallclock,
    "fsync-no-failpoint": rule_fsync_failpoint,
    "metrics-cardinality": rule_metrics_cardinality,
    "mutable-default": rule_mutable_default,
    "dict-iter-mutation": rule_dict_iter_mutation,
    "unused-import": rule_unused_import,
    "thread-start-in-ctor": rule_thread_start_in_ctor,
    "log-in-hot-loop": rule_log_in_hot_loop,
}


def lint_file(path: str) -> list[Violation]:
    ctx = load_file(path)
    if ctx is None:
        return []
    out: list[Violation] = []
    for fn in RULES.values():
        out.extend(fn(ctx))
    return out


def iter_py_files(paths: list[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


# -- baseline --------------------------------------------------------------

def load_baseline(path: str) -> dict[tuple, str]:
    out: dict[tuple, str] = {}
    if not os.path.exists(path):
        return out
    for ln in open(path, encoding="utf-8"):
        ln = ln.rstrip("\n")
        if not ln or ln.startswith("#"):
            continue
        parts = ln.split("|", 4)
        if len(parts) != 5:
            print(f"bcoslint: malformed baseline entry ignored: {ln!r}",
                  file=sys.stderr)
            continue
        rule, p, scope, fpr, just = parts
        out[(rule, p, scope, fpr)] = just
    return out


def write_baseline(path: str, violations: list[Violation],
                   old: dict[tuple, str]) -> None:
    lines = [
        "# bcoslint baseline — grandfathered violations with justifications.",
        "# Format: rule|path|scope|fingerprint|justification",
        "# A NEW violation (not listed here) fails the lint gate. Prefer",
        "# FIXING over baselining; every entry must say WHY it is correct.",
    ]
    seen: set[tuple] = set()
    for v in sorted(violations, key=lambda v: (v.rule, v.path, v.line)):
        if v.key in seen:
            continue
        seen.add(v.key)
        just = old.get(v.key, "TODO: justify or fix")
        lines.append(f"{v.rule}|{v.path}|{v.scope}|{v.fingerprint}|{just}")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation (ignore the baseline)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current violations, "
                    "keeping existing justifications")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in RULES)
        extra = {"lock-order": " (also emits blocking-under-lock)"}
        for r in RULES:
            print(f"{r:<{width}}{extra.get(r, '')}")
        return 0

    paths = args.paths or [os.path.join(REPO, p) for p in DEFAULT_PATHS]
    violations: list[Violation] = []
    nfiles = 0
    for f in iter_py_files(paths):
        nfiles += 1
        violations.extend(lint_file(f))

    if args.update_baseline:
        old = load_baseline(args.baseline)
        write_baseline(args.baseline, violations, old)
        print(f"bcoslint: baseline rewritten with "
              f"{len({v.key for v in violations})} entr(y/ies) -> "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    fresh = [v for v in violations if v.key not in baseline]
    stale = set(baseline) - {v.key for v in violations}

    for v in fresh:
        print(v.render())
    if stale:
        print(f"bcoslint: {len(stale)} stale baseline entr(y/ies) — "
              "run --update-baseline to prune:", file=sys.stderr)
        for key in sorted(stale):
            print(f"    {key[0]}|{key[1]}|{key[2]}", file=sys.stderr)
    grandfathered = len(violations) - len(fresh)
    print(f"bcoslint: {nfiles} files, {len(fresh)} new violation(s), "
          f"{grandfathered} grandfathered, {len(stale)} stale")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
