#!/usr/bin/env python3
"""game-day — run a fault schedule against a real local cluster.

    tools/gameday.py --schedule ci-smoke  -o /tmp/gd
    tools/gameday.py --schedule soak
    tools/gameday.py --schedule my-day.json --report report.json

Builds a fresh multi-node chain under `-o`, drives production-shaped
scenario load (open-loop Poisson at a calibrated fraction of capacity)
while the schedule fires faults — kill -9, asymmetric partitions,
Byzantine peers, armed failpoints, aggressor clients — and asserts the
operator-facing invariants after every phase (clean getAuditReport,
converged heads, healthz ok within the recovery SLO, bounded write p99)
plus end-of-day byte-identical c_balance across every node's storage.

Emits bench rows (gameday_phase / gameday_post_soak_tps /
gameday_write_p99_ms) as JSON lines on stdout for benchmark/bench.py
pickup; exits nonzero naming the failed phase AND invariant.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fisco_bcos_tpu.testing.gameday import (  # noqa: E402
    BUILTIN_SCHEDULES, GameDay, GameDayFailure)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="fault-schedule orchestrator over a real cluster")
    ap.add_argument("--schedule", required=True,
                    help="builtin name (%s) or a JSON schedule file"
                         % ", ".join(sorted(BUILTIN_SCHEDULES)))
    ap.add_argument("-o", "--out-dir", default="",
                    help="cluster directory (default: a temp dir, "
                         "removed on success, kept on failure)")
    ap.add_argument("--report", default="",
                    help="write the full day report JSON here")
    ap.add_argument("--keep", action="store_true",
                    help="keep the cluster directory even on success")
    args = ap.parse_args()

    if args.schedule in BUILTIN_SCHEDULES:
        schedule = BUILTIN_SCHEDULES[args.schedule]
    else:
        with open(args.schedule) as f:
            schedule = json.load(f)

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="gameday-")
    day = GameDay(schedule, out_dir,
                  emit=lambda row: print(json.dumps(row), flush=True),
                  log=lambda msg: print(f"# {msg}", file=sys.stderr,
                                        flush=True))
    try:
        report = day.run()
    except GameDayFailure as exc:
        print(f"GAME DAY FAILED — phase {exc.phase!r}, invariant "
              f"{exc.invariant!r}: {exc.detail}", file=sys.stderr)
        print(f"cluster kept for inspection: {out_dir}", file=sys.stderr)
        return 1
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
    print(f"# game day ok: {json.dumps(report)[:400]}", file=sys.stderr)
    if not args.keep and not args.out_dir:
        shutil.rmtree(out_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
