#!/usr/bin/env bash
# One-command sanitizer + differential-fuzz gate for the native engines
# (VERDICT r4 #8; SURVEY §5 row 34 — the reference's
# cmake -DSANITIZE_ADDRESS/-DSANITIZE_THREAD CI jobs, cmake/Options.cmake:57).
#
#   tools/sanitize_ci.sh            # full gate: ASan+UBSan, TSan, fuzz
#   tools/sanitize_ci.sh --fast     # skip the @slow deep differential fuzz
#   tools/sanitize_ci.sh --lint     # ONLY the concurrency-correctness
#                                   # plane: bcoslint (lexical) AND
#                                   # bcosflow (whole-program plane
#                                   # contracts) clean against their
#                                   # committed baselines — same exit-code
#                                   # convention: 1 iff a NEW finding —
#                                   # then an ARMED
#                                   # (BCOS_LOCKCHECK=1) 4-node smoke
#                                   # asserting zero lock-order cycles and
#                                   # zero blocking-while-locked hits with
#                                   # bcos_lock_* hold metrics live
#   tools/sanitize_ci.sh --chaos    # ONLY the multi-process fault gate:
#                                   # 4 OS-process TLS chain, kill -9 a node
#                                   # mid-stream, assert it rejoins to the
#                                   # same state root (tests/test_chaos_e2e)
#   tools/sanitize_ci.sh --gameday  # ONLY the game-day orchestration gate:
#                                   # the ci-smoke fault schedule
#                                   # (tools/gameday.py) against a real
#                                   # 4-node cluster under scenario load —
#                                   # clean audit + converged heads +
#                                   # health SLO + bounded write p99 +
#                                   # byte-identical c_balance, with the
#                                   # gameday_* rows under the perf gate
#   tools/sanitize_ci.sh --faults   # ONLY the failpoint/health smoke: boot
#                                   # a 4-node chain, arm one storage and
#                                   # one consensus failpoint at runtime
#                                   # via the ops endpoint (/failpoints),
#                                   # assert convergence, a clean
#                                   # getAuditReport on every node, and
#                                   # the /healthz + bcos_node_health
#                                   # gauge round-trip
#   tools/sanitize_ci.sh --ingest   # ONLY the continuous-batching smoke:
#                                   # short chain_bench --rpc-clients run,
#                                   # assert the lane coalesces (mean batch
#                                   # > 1) and emits an rpc_ingest_tps row
#   tools/sanitize_ci.sh --snapshot # ONLY the checkpoint smoke: export a
#                                   # snapshot from a live WAL-backed chain,
#                                   # wipe a fresh data dir, import, verify
#                                   # identical head hash + state root and
#                                   # emit the snap_sync_seconds bench row
#   tools/sanitize_ci.sh --pipeline # ONLY the pipelined-block-production
#                                   # smoke: 4-node chain, speculative
#                                   # execution + off-thread commit engage,
#                                   # byte-identical state across nodes, and
#                                   # the stage-occupancy bench row
#   tools/sanitize_ci.sh --rpc      # ONLY the read-plane smoke: boot a
#                                   # node, issue a keep-alive JSON-RPC 2.0
#                                   # batch, assert cache-hit metrics
#                                   # increment and a post-commit query
#                                   # serves the cached bytes
#   tools/sanitize_ci.sh --subs     # ONLY the push-plane smoke: boot a
#                                   # real daemon, attach 200 WS
#                                   # subscribers through the admission
#                                   # plane, kill one commit mid-stream
#                                   # (storage failpoint), assert no
#                                   # stale push ever reached a client
#                                   # and commit->client notify latency
#                                   # stays bounded
#   tools/sanitize_ci.sh --storage  # ONLY the disk-engine smoke: boot a
#                                   # [storage] backend = disk daemon,
#                                   # commit writes, kill -9 it, re-boot
#                                   # and verify manifest + WAL-tail
#                                   # recovery (no full-log replay) with
#                                   # identical balances + head, then the
#                                   # storage_compare bench row
#   tools/sanitize_ci.sh --obs      # ONLY the observability smoke: boot a
#                                   # daemon, submit txs under a client
#                                   # traceparent, fetch the trace by id
#                                   # via getTrace, parse /metrics off the
#                                   # RPC edge, reconcile the
#                                   # bcos_tx_stage_seconds stage sums
#                                   # against measured e2e latency, and
#                                   # emit the trace_profile_summary row
#   tools/sanitize_ci.sh --overload # ONLY the overload-control smoke:
#                                   # 4 real daemons with per-client edge
#                                   # budgets, an aggressor floods while a
#                                   # polite client keeps committing with
#                                   # bounded latency, -32005 rejects are
#                                   # observed, and health returns to ok
#                                   # after the storm; then the
#                                   # chain_bench --overload goodput row
#   tools/sanitize_ci.sh --zk       # ONLY the ZK proof plane smoke: real
#                                   # daemons, commit txs, fetch getProof
#                                   # over JSON-RPC, verify tx/receipt/
#                                   # state proofs client-side against the
#                                   # sealed header roots, reject tampered
#                                   # proof/value/root, round-trip the
#                                   # batched verifyProofs entry, then the
#                                   # chain_bench --proof-bench rows
#   tools/sanitize_ci.sh --profile  # ONLY the continuous-profiling smoke:
#                                   # real 4-node daemon chain, /profile
#                                   # returns folded stacks naming a
#                                   # scheduler + lane frame, a slow-span
#                                   # burst profile is retrievable by its
#                                   # trace id via getTrace, bcos_lane_*
#                                   # occupancy series live on /metrics,
#                                   # chain_bench --profile-attrib row,
#                                   # then tools/perf_gate.py report-only
#                                   # against the recorded trajectory
#   tools/sanitize_ci.sh --workers  # ONLY the out-of-process execution
#                                   # smoke: 4 real daemons with
#                                   # [scheduler] workers = 1, RPC writes,
#                                   # SIGKILL one node's execution worker
#                                   # mid-stream — the scheduler falls
#                                   # back in-process, the health plane
#                                   # respawns the worker, the respawned
#                                   # worker executes blocks, the chain
#                                   # converges to identical heads +
#                                   # byte-identical c_balance with a
#                                   # clean getAuditReport everywhere
#   tools/sanitize_ci.sh --seals    # ONLY the quorum-certificate smoke:
#                                   # 4 real TLS daemons with [consensus]
#                                   # seal_mode = cert, RPC writes,
#                                   # converged heads + clean audit on
#                                   # every node, every committed header
#                                   # carries ONE certificate whose wire
#                                   # bytes undercut the same quorum as
#                                   # 2f+1 loose seals, and the seal-bytes
#                                   # gauge + cert-verify counters are
#                                   # live on getSystemStatus.consensus
#   tools/sanitize_ci.sh --groups   # ONLY the multi-group smoke: ONE
#                                   # daemon hosting two groups ([groups]
#                                   # ini), disjoint writes routed by the
#                                   # group RPC param, per-group head
#                                   # hashes diverge, a cross-group
#                                   # transfer settles atomically, and the
#                                   # shared crypto lane's batch metric
#                                   # shows real (>1) merged batches
#
# Exit 0 = every stage clean. Each stage rebuilds the sanitizer variants
# from the CURRENT sources (the src-hash stamp keeps them honest) and runs
# the relevant suites with the sanitized libraries injected via the
# FBTPU_*_LIB loader seams.

set -euo pipefail
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

run_lint_stage() {
  echo "== [lint] bcoslint: repo invariants vs the committed baseline"
  local t0 t1
  t0=$SECONDS
  python tools/bcoslint.py
  t1=$SECONDS
  echo "== [lint] bcoslint clean in $((t1 - t0))s"
  echo "== [lint] bcosflow: whole-program plane contracts vs the baseline"
  t0=$SECONDS
  python tools/bcosflow.py
  t1=$SECONDS
  echo "== [lint] bcosflow clean in $((t1 - t0))s"
  echo "== [lint] armed lockcheck smoke: 4-node chain under BCOS_LOCKCHECK=1"
  BCOS_LOCKCHECK=1 JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" \
    timeout -k 10 600 python - <<'EOF'
import sys, time
sys.path.insert(0, "benchmark")
from fisco_bcos_tpu.analysis import lockcheck as lc
assert lc.armed(), "BCOS_LOCKCHECK=1 did not arm the checker"
from chain_bench import _build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.protocol import Transaction

nodes, gateways, _ = _build_chain(False, "host", 50)
suite = nodes[0].suite
kp = suite.generate_keypair(b"lint-smoke")
txs = [Transaction(to=pc.BALANCE_ADDRESS,
                   input=pc.encode_call(
                       "register",
                       lambda w, i=i: w.blob(b"ls%d" % i).u64(1 + i)),
                   nonce=f"ls-{i}", block_limit=300).sign(suite, kp)
       for i in range(120)]
for node in nodes:
    node.start()
try:
    for s in range(0, 120, 30):
        nodes[(s // 30) % 4].txpool.submit_batch(txs[s:s + 30])
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if all(n.ledger.total_tx_count() >= 120 for n in nodes):
            break
        time.sleep(0.05)
    assert all(n.ledger.total_tx_count() >= 120 for n in nodes), \
        [n.ledger.total_tx_count() for n in nodes]
finally:
    for node in nodes:
        node.stop()
    for gw in set(gateways):
        gw.stop()
rep = lc.report()
assert rep["edges"], "armed run recorded no lock-order edges at all"
lc.assert_clean()
from fisco_bcos_tpu.utils.metrics import REGISTRY
snap = REGISTRY.snapshot()
holds = [k for k in snap["histograms"] if k.startswith("bcos_lock_hold")]
assert holds, "no bcos_lock_hold_seconds series emitted"
print("sanitize_ci: LINT STAGE CLEAN "
      f"(edges={len(rep['edges'])}, cycles=0, blocking=0, "
      f"lock_series={len(holds)})")
EOF
}

run_profile_stage() {
  echo "== [profile] continuous-profiling smoke: real 4-node daemon chain," \
       "/profile folded stacks + flamegraph, slow-span burst by trace id,"
  echo "==           bcos_profile_*//bcos_lane_* series, perf gate" \
       "report-only vs the recorded trajectory"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import configparser, http.client, json, os, re, shutil, signal
import subprocess, sys, tempfile, time
sys.path.insert(0, "tools")
from build_chain import build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import SdkClient, TransactionBuilder
from fisco_bcos_tpu.crypto.suite import make_suite

work = tempfile.mkdtemp(prefix="profile-smoke-")
procs = []
try:
    from fisco_bcos_tpu.testing.chaos import free_port_block
    port = free_port_block(8)
    info = build_chain(work, 4, consensus="pbft", rpc_base_port=port,
                       p2p_base_port=port + 4, crypto_backend="host")
    # arm the plane's burst path deterministically: sampled client traces
    # + a slow-span threshold every sendTransaction span clears
    for ent in info["nodes"]:
        ini = os.path.join(ent["dir"], "config.ini")
        cp = configparser.ConfigParser(strict=False)
        cp.read(ini)
        cp["trace"]["slow_ms"] = "5"
        cp["profile"]["hz"] = "19"
        cp["profile"]["burst_s"] = "0.5"
        with open(ini, "w") as f:
            cp.write(f)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    for ent in info["nodes"]:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "fisco_bcos_tpu", ent["dir"],
             "--log-file", os.path.join(ent["dir"], "daemon.log")],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env))
    cli = SdkClient(f"http://127.0.0.1:{port}", group=info["group_id"])
    end = time.monotonic() + 120
    while time.monotonic() < end:
        try:
            cli.get_block_number(); break
        except Exception:
            time.sleep(0.25)
    else:
        raise TimeoutError("rpc never came up")

    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"profile-smoke")
    builder = TransactionBuilder(suite, None, chain_id=info["chain_id"],
                                 group_id=info["group_id"])
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    tid = os.urandom(16).hex()
    for i in range(6):
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w, i=i: w.blob(b"pf%d" % i)
                                          .u64(10 + i)),
                           nonce=f"pf{i}", block_limit=100)
        body = json.dumps({"jsonrpc": "2.0", "id": i,
                           "method": "sendTransaction",
                           "params": [info["group_id"], "",
                                      "0x" + tx.encode().hex()]})
        conn.request("POST", "/", body=body.encode(),
                     headers={"traceparent":
                              f"00-{tid}-00f067aa0ba902b7-01"})
        resp = json.loads(conn.getresponse().read())
        assert resp["result"]["status"] == 0, resp

    # 1) /profile (rpc edge): non-empty folded stacks naming at least one
    # scheduler and one lane frame (the continuous-batching ingest lane
    # dispatcher IS resident on every node; role prefix `ingest`)
    conn.request("GET", "/profile?seconds=2")
    r = conn.getresponse(); folded = r.read().decode()
    assert r.status == 200 and folded.strip(), (r.status, folded[:200])
    assert "scheduler.py:" in folded, folded[:800]
    assert "ingest;" in folded and "ingest.py:" in folded, folded[:800]
    # 2) the flamegraph renderer serves self-contained HTML
    conn.request("GET", "/profile?fmt=flame")
    r = conn.getresponse(); html = r.read().decode()
    assert r.status == 200 and "<html" in html and "FOLDED" in html

    # 3) slow-span burst: retrievable BY TRACE ID via getTrace (poll — the
    # burst runs 0.5 s after the span fires) and flagged in listTraces
    deadline = time.monotonic() + 30
    prof = None
    while time.monotonic() < deadline:
        doc = cli.request("getTrace", [info["group_id"], "", tid])
        prof = doc.get("profile")
        if prof:
            break
        time.sleep(0.5)
    assert prof and prof["folded"].strip(), "no burst profile for trace"
    assert prof["traceId"] == tid and prof["samples"] > 0, prof
    lst = cli.request("listTraces", [info["group_id"], "", 50])
    flagged = [t for t in lst["traces"] if t.get("profiled")]
    assert any(t["traceId"] == tid for t in flagged), lst["traces"][:3]

    # 4) profiler + getSystemStatus surfaces
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    assert "bcos_profile_samples_total" in text, text[:400]
    st = cli.request("getSystemStatus", [info["group_id"], ""])
    assert st["profile"]["armed"] and st["profile"]["samples"] > 0, \
        st["profile"]
    print("sanitize_ci: PROFILE daemon smoke clean "
          f"(folded_lines={len(folded.splitlines())}, "
          f"burst_samples={prof['samples']}, "
          f"profiled_traces={len(flagged)})")
finally:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=30)
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [profile] crypto-lane occupancy telemetry: 2 groups, one shared" \
       "lane, bcos_lane_* series live"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import shutil, tempfile, threading, time
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.daemon import NodeDaemon
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.tool.config import ChainConfig, save_node_config
from fisco_bcos_tpu.utils.metrics import REGISTRY
from fisco_bcos_tpu.crypto.suite import make_suite

work = tempfile.mkdtemp(prefix="lane-occ-smoke-")
try:
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"lane-occ")
    cfg = NodeConfig(groups=["group0", "group1"], consensus="solo",
                     crypto_backend="host", min_seal_time=0.0,
                     storage_path="data", rpc_port=0, p2p_port=0)
    chain = ChainConfig(consensus_type="solo", sealers=[kp.pub_bytes])
    save_node_config(work, cfg, chain, kp.secret)
    daemon = NodeDaemon(work)
    daemon.start()
    try:
        nodes = [daemon.manager.node(g) for g in ("group0", "group1")]
        bursts = [[Transaction(to=pc.BALANCE_ADDRESS,
                               input=pc.encode_call(
                                   "register",
                                   lambda w, i=i: w.blob(
                                       b"%s-%d" % (g.encode(), i)).u64(1)),
                               nonce=f"lo-{g}-{i}", group_id=g,
                               block_limit=100).sign(suite, kp)
                   for i in range(64)]
                  for g in ("group0", "group1")]
        ths = [threading.Thread(
            target=lambda n=n, b=b: n.txpool.submit_batch(b), daemon=True)
            for n, b in zip(nodes, bursts)]
        for t in ths: t.start()
        for t in ths: t.join(60)
        time.sleep(0.5)  # let the lane dispatcher drain its last batch
        # occupancy telemetry on the shared lane (crypto/lane.py)
        lane = daemon.manager.crypto_lane_stats()["ecdsa"]
        occ = lane["occupancy"]
        assert occ and any(o["device_calls"] > 0 for o in occ.values()), occ
        text = REGISTRY.prometheus_text()
        for series in ("bcos_lane_dispatch_seconds", "bcos_lane_batch_items",
                       "bcos_lane_merge_requests"):
            assert series in text, f"missing {series}"
        # the lane dispatcher thread shows up under the `lane` role in a
        # live capture (the profiler names the crypto lane frame)
        from fisco_bcos_tpu.analysis.profiler import PROFILER
        folded = PROFILER.capture(1.0)
        assert "lane;" in folded and "lane.py:" in folded, folded[:800]
        print("sanitize_ci: PROFILE lane-occupancy clean "
              f"(ops={sorted(occ)}, "
              f"mean_batch={lane['mean_device_batch']})")
    finally:
        daemon.shutdown()
finally:
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [profile] chain_bench --profile-attrib: GIL-holder table +" \
       "self-cost A/B"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python benchmark/chain_bench.py --profile-attrib -n 2000 \
    --profile-runs 1 --backend host 2>/dev/null \
    | grep '"metric": "profile_attrib_summary"'
  echo "== [profile] perf gate, report-only, vs the recorded trajectory"
  python tools/perf_gate.py \
    --candidate "$(ls BENCH_r*.json | tail -1)" --report-only
}

if [ "${1:-}" = "--lint" ]; then
  run_lint_stage
  exit 0
fi

if [ "${1:-}" = "--profile" ]; then
  run_profile_stage
  echo "sanitize_ci: PROFILE STAGE CLEAN"
  exit 0
fi

if [ "${1:-}" = "--ingest" ]; then
  echo "== [ingest] continuous-batching lane smoke: 4 HTTP clients," \
       "200 txs through the 4-node chain's ingest lane"
  OUT="$(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python benchmark/chain_bench.py --rpc-clients 4 -n 200 --backend host \
    2>/dev/null | grep '"metric": "rpc_ingest_tps"')"
  echo "$OUT"
  python - "$OUT" <<'EOF'
import json, sys
row = json.loads(sys.argv[1])
assert not row.get("timed_out"), f"chain wedged: {row}"
assert row["txs_committed"] >= 200, row
assert row["mean_batch"] > 1.0, f"lane not coalescing: {row}"
assert row["recover_calls_per_tx"] < 1.0, row
print("sanitize_ci: INGEST STAGE CLEAN "
      f"(tps={row['tps']}, mean_batch={row['mean_batch']}, "
      f"recover/tx={row['recover_calls_per_tx']})")
EOF
  exit 0
fi

if [ "${1:-}" = "--rpc" ]; then
  echo "== [rpc] read-plane smoke: keep-alive batch request +" \
       "commit-coherent query cache"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 300 \
    python - <<'EOF'
import json
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.sdk.client import SdkClient

node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                       rpc_port=0))
node.start()
try:
    kp = node.suite.generate_keypair(b"rpc-smoke")
    def register(i):
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register",
                             lambda w: w.blob(b"rs%d" % i).u64(10 + i)),
                         nonce=f"rs{i}", block_limit=100).sign(node.suite, kp)
        rc = node.txpool.wait_for_receipt(
            node.send_transaction(tx).tx_hash, 30)
        assert rc is not None and rc.status == 0, rc
    for i in range(3):
        register(i)

    sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
    # ONE keep-alive connection, ONE JSON-RPC 2.0 batch body
    head = node.ledger.current_number()
    resps = sdk.request_batch([
        ("getBlockNumber", ["group0", ""]),
        ("getBlockByNumber", ["group0", "", head, False, False]),
        ("getBlockByNumber", ["group0", "", head, False, False]),
    ])
    assert len(resps) == 3 and all("result" in r for r in resps), resps
    assert resps[0]["result"] == head
    assert json.dumps(resps[1]["result"]) == json.dumps(resps[2]["result"])
    s0 = node.query_cache.stats()
    assert s0["hits"] >= 1, s0  # identical in-batch query served cached

    # post-commit: a NEW block's responses serve from the primed cache,
    # byte-for-byte identical across requests on the same connection
    register(3)
    new_head = node.ledger.current_number()
    assert new_head > head
    b1 = sdk.get_block_by_number(new_head)
    b2 = sdk.get_block_by_number(new_head)
    assert json.dumps(b1) == json.dumps(b2)
    s1 = node.query_cache.stats()
    assert s1["hits"] > s0["hits"], (s0, s1)
    print("sanitize_ci: RPC STAGE CLEAN "
          f"(hits={s1['hits']}, hit_rate={s1['hit_rate']}, "
          f"entries={s1['entries']})")
finally:
    node.stop()
EOF
  exit 0
fi

if [ "${1:-}" = "--subs" ]; then
  echo "== [subs] push-plane smoke: real daemon, 200 WS subscribers" \
       "through admission, one commit killed mid-stream, no stale push"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import configparser, os, signal, subprocess, sys, tempfile, threading, time
import urllib.request
sys.path.insert(0, "tools")
from build_chain import build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.sdk.client import SdkClient, TransactionBuilder
from fisco_bcos_tpu.sdk.ws import WsSdkClient
from fisco_bcos_tpu.testing.chaos import free_port_block

N_SUBS, N_TX = 200, 12
work = tempfile.mkdtemp(prefix="subs-smoke-")
proc, subs = None, []
try:
    port = free_port_block(4)
    info = build_chain(work, 1, consensus="solo", rpc_base_port=port,
                       p2p_base_port=port + 1, metrics_base_port=port + 2,
                       crypto_backend="host")
    node_dir = info["nodes"][0]["dir"]
    ws_port = port + 3
    cfgp = os.path.join(node_dir, "config.ini")
    cp = configparser.ConfigParser()
    cp.read(cfgp)
    cp["rpc"]["ws_port"] = str(ws_port)
    with open(cfgp, "w") as f:
        cp.write(f)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               BCOS_FAILPOINTS_OPS="1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fisco_bcos_tpu", node_dir,
         "--log-file", os.path.join(node_dir, "daemon.log")],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
    cli = SdkClient(f"http://127.0.0.1:{port}", group=info["group_id"])
    end = time.monotonic() + 120
    while time.monotonic() < end:
        try:
            cli.get_block_number()
            break
        except Exception:
            time.sleep(0.25)
    else:
        raise TimeoutError("rpc never came up")

    # the subscriber fleet rides the SAME admission plane as RPC reads
    print(f"attaching {N_SUBS} WS subscribers...", flush=True)
    subs = [WsSdkClient("127.0.0.1", ws_port, group=info["group_id"])
            for _ in range(N_SUBS)]
    for c in subs:
        c.subscribe("newBlockHeaders")

    # probe drains ITS stream live: per-event latency vs the sealed-at
    # stamp (generous cross-process bound — includes execute + commit)
    probe = subs[0]
    probe_lat = []

    def drain_probe():
        while True:
            ev = probe.next_event(timeout=1.0)
            if ev is None:
                if stop_probe.is_set():
                    return
                continue
            ts = (ev.get("result") or {}).get("timestamp")
            if ts:
                probe_lat.append(time.time() * 1000 - ts)

    stop_probe = threading.Event()
    pt = threading.Thread(target=drain_probe, daemon=True)
    pt.start()

    # the attach storm can trip the health plane into degraded (writes
    # shed) on small hosts — wait for ok, then ride out residual sheds
    def wait_ok(deadline=60):
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port + 2}/healthz",
                    timeout=5).read()
                return
            except Exception:
                time.sleep(0.5)

    wait_ok()
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"subs-smoke")
    builder = TransactionBuilder(suite, None, chain_id=info["chain_id"],
                                 group_id=info["group_id"])
    for i in range(N_TX):
        if i == 4:
            # kill ONE commit mid-stream: the aborted block must never
            # be pushed to any subscriber (double-invalidation contract)
            url = (f"http://127.0.0.1:{port + 2}/failpoints"
                   f"?arm=scheduler.commit.entry=raise*1")
            urllib.request.urlopen(url, timeout=10).read()
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w, i=i: w.blob(b"sb%d" % i)
                                          .u64(10 + i)),
                           nonce=f"sb{i}", block_limit=500)
        for attempt in range(40):
            try:
                cli.send_transaction(tx, wait=False)
                break
            except Exception:  # degraded shed / brief edge hiccup
                time.sleep(0.5)
        else:
            raise RuntimeError(f"tx {i} shed for 20s straight")
        time.sleep(0.2)
    end = time.monotonic() + 120
    while time.monotonic() < end:
        if cli.request("getTotalTransactionCount",
                       [info["group_id"], ""])["transactionCount"] >= N_TX:
            break
        time.sleep(0.25)
    head = cli.get_block_number()
    assert head >= 8, f"chain wedged at {head} after the killed commit"
    canon = {n: cli.request("getBlockHashByNumber",
                            [info["group_id"], "", n])
             for n in range(1, head + 1)}

    # every subscriber sees the final head; every pushed header matches
    # the canonical chain byte-for-byte (no stale push survived the
    # killed commit), across ALL 200 streams
    events = 0
    for ci, c in enumerate(subs[1:], start=1):
        saw_head, end = False, time.monotonic() + 30
        while not saw_head and time.monotonic() < end:
            ev = c.next_event(timeout=1.0)
            if ev is None:
                continue
            r = ev.get("result") or {}
            events += 1
            assert r.get("hash") == canon.get(r.get("number")), \
                (ci, r.get("number"), r.get("hash"))
            saw_head = r.get("number") == head
        assert saw_head, f"subscriber {ci} never saw head {head}"
    stop_probe.set()
    pt.join(timeout=5)
    lat = sorted(probe_lat)
    p99 = lat[int(0.99 * (len(lat) - 1))] if lat else 0.0
    assert lat and p99 < 5000, f"notify p99 {p99:.0f}ms (n={len(lat)})"
    print(f"sanitize_ci: SUBS STAGE CLEAN (head={head}, "
          f"events={events}, notify_p99={p99:.0f}ms)")
finally:
    for c in subs:
        try:
            c.close()
        except Exception:
            pass
    if proc is not None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
EOF
  exit 0
fi

if [ "${1:-}" = "--snapshot" ]; then
  echo "== [snapshot] checkpoint smoke: export -> wipe -> import ->" \
       "verify state root (WAL-backed solo chain)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 300 \
    python - <<'EOF'
import shutil, tempfile
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import Ledger
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.snapshot import export_snapshot, install_snapshot
from fisco_bcos_tpu.storage.wal import WalStorage

work = tempfile.mkdtemp(prefix="snap-smoke-")
try:
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           storage_path=work + "/data"))
    node.start()
    kp = node.suite.generate_keypair(b"snap-smoke")
    for i in range(5):
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register",
                             lambda w, i=i: w.blob(b"a%d" % i).u64(1)),
                         nonce=f"s{i}", block_limit=100).sign(node.suite, kp)
        rc = node.txpool.wait_for_receipt(
            node.send_transaction(tx).tx_hash, 30)
        assert rc is not None and rc.status == 0, rc
    node.stop()
    head = node.ledger.current_number()
    want_hash = node.ledger.header_by_number(head).hash(node.suite)
    want_root = node.ledger.header_by_number(head).state_root
    manifest, chunks = export_snapshot(node.storage, node.ledger,
                                       node.suite, chunk_bytes=4096)
    node.storage.close()

    # disaster: the data dir is gone; import into a brand-new WAL store
    shutil.rmtree(work + "/data")
    fresh = WalStorage(work + "/data2")
    import numpy as np
    def verify_seals(header):
        sealer = node.keypair.pub_bytes
        assert list(header.sealer_list) == [sealer]
        hh = header.hash(node.suite)
        ok = node.suite.verify_batch(
            [hh], [header.signature_list[0][1]], [sealer])
        return bool(np.asarray(ok)[0])
    install_snapshot(manifest, chunks, fresh, node.suite, verify_seals)
    led = Ledger(fresh, node.suite)
    assert led.current_number() == head == manifest.height
    assert led.header_by_number(head).hash(node.suite) == want_hash
    assert led.header_by_number(head).state_root == want_root
    # executor state travelled too, not just chain metadata: the balances
    # the register txs wrote must be byte-identical on the imported side
    bal_keys = list(node.storage.keys("c_balance"))
    assert bal_keys and list(fresh.keys("c_balance")) == bal_keys
    for k in bal_keys:
        assert fresh.get("c_balance", k) == node.storage.get("c_balance", k)
    fresh.close()
    print("sanitize_ci: SNAPSHOT STAGE CLEAN "
          f"(height={head}, chunks={manifest.chunk_count}, "
          f"bytes={manifest.total_bytes})")
finally:
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [snapshot] join-time bench row (replay vs snap-sync)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 300 \
    python benchmark/chain_bench.py --sync-bench --sync-blocks 20 \
    2>/dev/null | grep '"metric": "snap_sync_seconds"'
  exit 0
fi

if [ "${1:-}" = "--pipeline" ]; then
  echo "== [pipeline] pipelined block production smoke: 4-node chain," \
       "speculative execution + off-thread commit, byte-identical state"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import sys, time
sys.path.insert(0, "benchmark")
from chain_bench import _build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.protocol import Transaction

nodes, gateways, _ = _build_chain(False, "host", 50)
# slow node 0's storage commit slightly so commit(N) reliably overlaps
# the next height's consensus+execution (the smoke must PROVE the
# pipeline engaged, not just that the chain still works)
orig = nodes[0].storage.commit
nodes[0].storage.commit = lambda n, _o=orig: (time.sleep(0.1), _o(n))[1]
suite = nodes[0].suite
kp = suite.generate_keypair(b"pipe-smoke")
txs = [Transaction(to=pc.BALANCE_ADDRESS,
                   input=pc.encode_call(
                       "register",
                       lambda w, i=i: w.blob(b"ps%d" % i).u64(1 + i)),
                   nonce=f"ps-{i}", block_limit=300).sign(suite, kp)
       for i in range(300)]
for node in nodes:
    node.start()
try:
    for s in range(0, 300, 75):
        nodes[(s // 75) % 4].txpool.submit_batch(txs[s:s + 75])
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        if all(n.ledger.total_tx_count() >= 300 for n in nodes):
            break
        time.sleep(0.05)
    assert all(n.ledger.total_tx_count() == 300 for n in nodes), \
        [n.ledger.total_tx_count() for n in nodes]
    stats = nodes[0].scheduler.pipeline_stats()
    assert stats["speculative_execs"] >= 1, \
        f"pipeline never engaged: {stats}"
    # byte-identical replicated state across all 4 nodes: head hash AND
    # the executor's balance table (per-changeset state_root alone does
    # NOT prove full-state equality — see PR 4's c_ prefix lesson)
    head = nodes[0].ledger.current_number()
    want_hash = nodes[0].ledger.header_by_number(head).hash(suite)
    bal_keys = sorted(nodes[0].storage.keys("c_balance"))
    assert bal_keys, "no balance rows written"
    for n in nodes[1:]:
        assert n.ledger.current_number() == head
        assert n.ledger.header_by_number(head).hash(suite) == want_hash
        assert sorted(n.storage.keys("c_balance")) == bal_keys
        for k in bal_keys:
            assert n.storage.get("c_balance", k) == \
                nodes[0].storage.get("c_balance", k)
    print("sanitize_ci: PIPELINE STAGE CLEAN "
          f"(blocks={head}, speculative_execs={stats['speculative_execs']}, "
          f"overlap_commits={stats['overlap_commits']}, "
          f"commit_stage_s={stats['stages'].get('commit', {}).get('seconds')})")
finally:
    for node in nodes:
        node.stop()
    for gw in set(gateways):
        gw.stop()
EOF
  echo "== [pipeline] stage-occupancy bench row"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python benchmark/chain_bench.py -n 1000 --backend host \
    --pipeline-profile 2>/dev/null | grep '"metric": "pipeline_'
  exit 0
fi

if [ "${1:-}" = "--workers" ]; then
  echo "== [workers] out-of-process execution smoke: 4 daemons with" \
       "[scheduler] workers = 1, SIGKILL a worker mid-stream, scheduler" \
       "falls back + health plane respawns, chain converges, clean audit"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python tools/workers_smoke.py
  echo "== [workers] columnar A/B bench row (object vs columnar ingest)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --columnar-compare -n 1000 \
    --backend host 2>/dev/null | grep '"metric": "columnar_tps"'
  echo "sanitize_ci: WORKERS STAGE CLEAN"
  exit 0
fi

if [ "${1:-}" = "--seals" ]; then
  echo "== [seals] quorum-certificate smoke: 4 TLS daemons in" \
       "seal_mode=cert, converged heads, clean audit, ONE cert per" \
       "block with fewer wire bytes than its own quorum as loose seals"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python - <<'EOF'
import tempfile
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

out = tempfile.mkdtemp(prefix="seals-smoke-")
with ChaosHarness(out, tls=True,
                  config_overrides={"seal_mode": "cert"}) as h:
    h.start_all()
    for i in range(h.n):
        h.wait_rpc_up(i)
    suite = h.suite()
    kp = suite.generate_keypair(b"seals-smoke")
    builder = TransactionBuilder(suite, None, chain_id=h.info["chain_id"],
                                 group_id=h.info["group_id"])
    for s in range(8):
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w: w.blob(b"s%d" % s)
                                          .u64(1)),
                           nonce=f"s-{s}", block_limit=500)
        h.client(s % h.n).send_transaction(tx, wait=False)
    h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 8,
                 timeout=240, what="commits in cert mode")
    height = h.wait_converged(range(h.n), min_height=1, timeout=240)
    ssz = suite.signature_size
    ratios = []
    for i in range(h.n):
        rep = h.audit_report(i)
        assert rep["ok"], (i, rep)
        cons = h.client(i).request("getSystemStatus",
                                   [h.info["group_id"], ""])["consensus"]
        assert cons["sealMode"] == "cert", cons
        signers, cert_bytes = (cons["sealSignersPerBlock"],
                               cons["sealBytesPerBlock"])
        assert signers >= 3 and cert_bytes > 0, cons
        # the SAME quorum as legacy loose seals: i64 idx + blob frame +
        # signature per entry, plus the list length word
        loose = signers * (8 + 4 + ssz) + 8
        assert cert_bytes < loose, (i, cert_bytes, loose)
        ratios.append(round(cert_bytes / loose, 3))
    gauge = [ln for ln in h.metrics_text(0).splitlines()
             if ln.startswith("bcos_consensus_seal_bytes_per_block")]
    assert gauge, "seal-bytes gauge missing from /metrics"
    print(f"sanitize_ci: SEALS STAGE CLEAN (height={height}, "
          f"cert_vs_loose={ratios})")
EOF
  exit 0
fi

if [ "${1:-}" = "--groups" ]; then
  echo "== [groups] multi-group smoke: one daemon, two groups, routed RPC," \
       "cross-group transfer, shared crypto lane"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import json, shutil, tempfile, threading, time
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.daemon import NodeDaemon
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.sdk.client import SdkClient
from fisco_bcos_tpu.tool.config import ChainConfig, save_node_config

work = tempfile.mkdtemp(prefix="groups-smoke-")
try:
    from fisco_bcos_tpu.crypto.suite import make_suite
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"groups-smoke")
    cfg = NodeConfig(groups=["group0", "group1"], consensus="solo",
                     crypto_backend="host", min_seal_time=0.0,
                     storage_path="data", rpc_port=0, p2p_port=0)
    chain = ChainConfig(consensus_type="solo", sealers=[kp.pub_bytes])
    save_node_config(work, cfg, chain, kp.secret)
    daemon = NodeDaemon(work)
    daemon.start()
    try:
        assert daemon.manager is not None, "daemon did not boot multigroup"
        assert daemon.manager.groups() == ["group0", "group1"]
        url = f"http://127.0.0.1:{daemon.rpc.port}"
        sdk = SdkClient(url)

        def register(group, account, amount, nonce):
            tx = Transaction(to=pc.BALANCE_ADDRESS,
                             input=pc.encode_call(
                                 "register",
                                 lambda w: w.blob(account).u64(amount)),
                             nonce=nonce, group_id=group,
                             block_limit=100).sign(suite, kp)
            return sdk.request("sendTransaction",
                               [group, "", "0x" + tx.encode().hex(),
                                False, True, 30.0])

        # disjoint writes routed by the group param over ONE edge
        rc = register("group0", b"alice", 100, "g0-a")
        assert rc["status"] == 0, rc
        rc = register("group1", b"bob", 7, "g1-b")
        assert rc["status"] == 0, rc
        h0 = sdk.request("getBlockHashByNumber", ["group0", "", 1])
        h1 = sdk.request("getBlockHashByNumber", ["group1", "", 1])
        assert h0 and h1 and h0 != h1, "group heads did not diverge"

        # a real (>1) verify batch through the shared crypto lane: one
        # in-process burst per group, submitted concurrently
        nodes = [daemon.manager.node(g) for g in ("group0", "group1")]
        bursts = [[Transaction(to=pc.BALANCE_ADDRESS,
                               input=pc.encode_call(
                                   "register",
                                   lambda w, i=i: w.blob(
                                       b"%s-%d" % (g.encode(), i)).u64(1)),
                               nonce=f"b-{g}-{i}", group_id=g,
                               block_limit=100).sign(suite, kp)
                   for i in range(64)]
                  for g in ("group0", "group1")]
        ths = [threading.Thread(
            target=lambda n=n, b=b: n.txpool.submit_batch(b), daemon=True)
            for n, b in zip(nodes, bursts)]
        for t in ths: t.start()
        for t in ths: t.join(60)
        lane = daemon.manager.crypto_lane_stats()["ecdsa"]
        assert lane["mean_device_batch"] > 1.0, lane

        # cross-group transfer via RPC settles atomically
        tx = Transaction(to=pc.XSHARD_ADDRESS,
                         input=pc.encode_call(
                             "transferOut",
                             lambda w: w.blob(b"smoke-x").text("group1")
                             .blob(b"alice").blob(b"bob").u64(30)),
                         nonce="x-s", group_id="group0",
                         block_limit=100).sign(suite, kp)
        rc = sdk.request("sendTransaction",
                         ["group0", "", "0x" + tx.encode().hex(),
                          False, True, 30.0])
        assert rc["status"] == 0, rc
        bal_call = pc.encode_call("balanceOf", lambda w: w.blob(b"bob"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            out = sdk.request("call", ["group1", "",
                                       "0x" + pc.BALANCE_ADDRESS.hex(),
                                       "0x" + bal_call.hex()])
            if int(out["output"][2:], 16) == 37:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("cross-group credit never landed")
        out = sdk.request("call", ["group0", "",
                                   "0x" + pc.BALANCE_ADDRESS.hex(),
                                   "0x" + pc.encode_call(
                                       "balanceOf",
                                       lambda w: w.blob(b"alice")).hex()])
        assert int(out["output"][2:], 16) == 70
        # and an unknown group answers the dedicated error object
        try:
            sdk.request("getBlockNumber", ["nope"])
            raise AssertionError("unknown group did not error")
        except Exception as exc:
            assert "-32004" in str(exc) or "unknown group" in str(exc), exc
        print("sanitize_ci: GROUPS STAGE CLEAN "
              f"(lane_mean_batch={lane['mean_device_batch']}, "
              f"merged_calls={lane['merged_calls']}, "
              f"xshard={daemon.manager.coordinator.stats()})")
    finally:
        daemon.shutdown()
finally:
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [groups] scaling bench row (2 groups vs 1, interleaved medians)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --groups 2 --groups-compare \
    --cross-shard-pct 10 -n 1000 --backend host 2>/dev/null \
    | grep '"metric": "groups'
  exit 0
fi

if [ "${1:-}" = "--storage" ]; then
  echo "== [storage] disk-engine smoke: boot disk backend, write," \
       "SIGKILL, re-boot without replay, verify"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import os, re, shutil, signal, subprocess, sys, tempfile, time
sys.path.insert(0, "tools")
from build_chain import build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import SdkClient, TransactionBuilder
from fisco_bcos_tpu.crypto.suite import make_suite

work = tempfile.mkdtemp(prefix="storage-smoke-")
proc = None
try:
    from fisco_bcos_tpu.testing.chaos import free_port_block
    port = free_port_block(2)
    info = build_chain(work, 1, consensus="solo", rpc_base_port=port,
                       p2p_base_port=port + 1,
                       crypto_backend="host", storage_backend="disk")
    node_dir = info["nodes"][0]["dir"]
    # flush on every commit: kill -9 lands mid-flush/compaction territory
    cfgp = os.path.join(node_dir, "config.ini")
    cfg = open(cfgp).read()
    cfg = cfg.replace("memtable_mb = 64", "memtable_mb = 0")
    cfg = cfg.replace("compact_segments = 8", "compact_segments = 2")
    open(cfgp, "w").write(cfg)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")

    def boot():
        return subprocess.Popen(
            [sys.executable, "-m", "fisco_bcos_tpu", node_dir,
             "--log-file", os.path.join(node_dir, "daemon.log")],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)

    def wait_rpc(cli, deadline=120):
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            try:
                return cli.get_block_number()
            except Exception:
                time.sleep(0.25)
        raise TimeoutError("rpc never came up")

    proc = boot()
    cli = SdkClient(f"http://127.0.0.1:{port}", group=info["group_id"])
    wait_rpc(cli)
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"storage-smoke")
    builder = TransactionBuilder(suite, None, chain_id=info["chain_id"],
                                 group_id=info["group_id"])
    for i in range(6):
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w, i=i: w.blob(b"sk%d" % i)
                                          .u64(10 + i)),
                           nonce=f"ss{i}", block_limit=100)
        rc = cli.send_transaction(tx, wait=True)
        assert rc["status"] == 0, rc
    head = cli.get_block_number()
    head_hash = cli.request("getBlockHashByNumber",
                            [info["group_id"], "", head])
    assert head >= 1

    proc.send_signal(signal.SIGKILL)   # no flush, no goodbye
    proc.wait(timeout=30)
    proc = boot()                      # same data dir
    wait_rpc(cli)
    log = open(os.path.join(node_dir, "daemon.log")).read()
    recov = re.findall(r"\[ENGINE\]\[recovered\].*?segments=(\d+)"
                       r".*?wal_records=(\d+)", log)
    assert recov, "no engine recovery badge after kill -9"
    segments, wal_records = map(int, recov[-1])
    assert segments >= 1, "boot found no durable segments"
    assert wal_records <= 6, \
        f"boot replayed {wal_records} WAL records — that is a full replay"
    assert cli.get_block_number() == head
    assert cli.request("getBlockHashByNumber",
                       [info["group_id"], "", head]) == head_hash
    for i in range(6):
        out = cli.request("call", [info["group_id"], "",
                                   "0x" + pc.BALANCE_ADDRESS.hex(),
                                   "0x" + pc.encode_call(
                                       "balanceOf",
                                       lambda w, i=i: w.blob(b"sk%d" % i)
                                   ).hex()])
        assert int(out["output"][2:], 16) == 10 + i
    print("sanitize_ci: STORAGE STAGE CLEAN "
          f"(head={head}, segments={segments}, "
          f"wal_tail_records={wal_records})")
finally:
    if proc is not None and proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [storage] disk-vs-memory bench row"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --storage-compare -n 400 \
    --tx-count-limit 100 --storage-memtable-mb 1 2>/dev/null \
    | grep '"metric": "storage_compare"'
  echo "== [storage] wide-table scenario: key pages default-on," \
       "read-amp counters live"
  WT_ROW="$(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --scenario wide-table -n 400 \
    --scenario-accounts 2000 --scenario-window 4 \
    --tx-count-limit 100 2>/dev/null \
    | grep '"metric": "scenario_wide_table"')"
  WT_ROW="$WT_ROW" python - <<'EOF'
import json, os
row = json.loads(os.environ["WT_ROW"])
st = row["storage"]
assert st["key_page_size"] and st["key_page_size"] > 0, \
    f"key pages not on by default for disk: {st}"
assert st["backend_reads"] and st["backend_reads"] > 0, \
    f"read-amp counter backend_reads dead: {st}"
assert st["cache_hits"] and st["cache_hits"] > 0, \
    f"read-amp counter cache_hits dead: {st}"
print("sanitize_ci: STORAGE STAGE read-amp live "
      f"(key_page={st['key_page_size']}B, "
      f"backend_reads={st['backend_reads']}, "
      f"cache_hits={st['cache_hits']})")
EOF
  exit 0
fi

if [ "${1:-}" = "--obs" ]; then
  echo "== [obs] observability smoke: daemon + client traceparent ->" \
       "getTrace by id, /metrics parses, stage sums ~ e2e"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python - <<'EOF'
import http.client, json, os, re, shutil, signal, subprocess, sys
import tempfile, time
sys.path.insert(0, "tools")
from build_chain import build_chain
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import SdkClient, TransactionBuilder
from fisco_bcos_tpu.crypto.suite import make_suite

work = tempfile.mkdtemp(prefix="obs-smoke-")
proc = None
try:
    from fisco_bcos_tpu.testing.chaos import free_port_block
    port = free_port_block(2)
    info = build_chain(work, 1, consensus="solo", rpc_base_port=port,
                       p2p_base_port=port + 1, crypto_backend="host")
    node_dir = info["nodes"][0]["dir"]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fisco_bcos_tpu", node_dir,
         "--log-file", os.path.join(node_dir, "daemon.log")],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
    cli = SdkClient(f"http://127.0.0.1:{port}", group=info["group_id"])
    end = time.monotonic() + 120
    while time.monotonic() < end:
        try:
            cli.get_block_number(); break
        except Exception:
            time.sleep(0.25)
    else:
        raise TimeoutError("rpc never came up")

    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"obs-smoke")
    builder = TransactionBuilder(suite, None, chain_id=info["chain_id"],
                                 group_id=info["group_id"])
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    tid = os.urandom(16).hex()
    e2e = []
    for i in range(8):
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w, i=i: w.blob(b"ob%d" % i)
                                          .u64(10 + i)),
                           nonce=f"ob{i}", block_limit=100)
        body = json.dumps({"jsonrpc": "2.0", "id": i,
                           "method": "sendTransaction",
                           "params": [info["group_id"], "",
                                      "0x" + tx.encode().hex()]})
        t0 = time.perf_counter()
        # client-supplied W3C traceparent, sampled flag SET: the node
        # must retain this trace regardless of its local sample_rate
        conn.request("POST", "/", body=body.encode(),
                     headers={"traceparent":
                              f"00-{tid}-00f067aa0ba902b7-01"})
        r = conn.getresponse()
        assert r.getheader("traceparent", "").startswith(f"00-{tid}")
        resp = json.loads(r.read())
        assert resp["result"]["status"] == 0, resp
        e2e.append(time.perf_counter() - t0)

    # 1) the trace is retrievable BY ID via RPC and covers the write path
    spans = cli.request("getTrace", [info["group_id"], "", tid])["spans"]
    names = {s["name"] for s in spans}
    assert {"rpc.sendTransaction", "stage.execute", "stage.commit",
            "stage.notify"} <= names, sorted(names)

    # 2) /metrics (served from the RPC event-loop edge) parses cleanly
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_]+="(\\.|[^"\\])*"'
        r'(,[a-zA-Z_]+="(\\.|[^"\\])*")*\})? [0-9.eE+-]+(\s[0-9]+)?$')
    bad = [l for l in text.splitlines()
           if l and not l.startswith("#") and not line_re.match(l)]
    assert not bad, f"unparseable exposition lines: {bad[:3]}"

    # 3) bcos_tx_stage_seconds stage sums ~ measured e2e: mean per-block
    # stage-sum must land in the same ballpark as the closed-loop mean
    sums = {}
    for m in re.finditer(r'bcos_tx_stage_seconds_sum\{stage="(\w+)"\} '
                         r'([0-9.eE+-]+)', text):
        sums[m.group(1)] = float(m.group(2))
    blocks = cli.get_block_number()
    stage_mean = sum(v for k, v in sums.items()
                     if k not in ("crypto",)) / max(1, blocks)
    e2e_mean = sum(e2e) / len(e2e)
    ratio = stage_mean / e2e_mean
    assert 0.2 <= ratio <= 2.0, (sums, stage_mean, e2e_mean)
    print("sanitize_ci: OBS STAGE CLEAN "
          f"(spans={len(spans)}, stages={sorted(sums)}, "
          f"stage_mean={stage_mean*1000:.1f}ms, "
          f"e2e_mean={e2e_mean*1000:.1f}ms, ratio={ratio:.2f})")
finally:
    if proc is not None and proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    shutil.rmtree(work, ignore_errors=True)
EOF
  echo "== [obs] trace-profile decomposition row"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python benchmark/chain_bench.py --trace-profile --trace-txs 16 \
    --backend host 2>/dev/null | grep '"metric": "trace_profile_summary"'
  exit 0
fi

if [ "${1:-}" = "--faults" ]; then
  echo "== [faults] failpoint/health smoke: 4-node chain, arm one storage" \
       "and one consensus failpoint via the ops endpoint, assert" \
       "convergence + clean getAuditReport + health gauge round-trip"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python - <<'EOF'
import tempfile, time
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

out = tempfile.mkdtemp(prefix="faults-smoke-")
with ChaosHarness(out, tls=False) as h:
    h.start_all()
    for i in range(h.n):
        h.wait_rpc_up(i)
    # health gauge round-trip while healthy
    code, doc = h.healthz(0)
    assert code == 200 and doc["state"] == "ok", (code, doc)
    gauge = [ln for ln in h.metrics_text(0).splitlines()
             if ln.startswith("bcos_node_health")]
    assert gauge and float(gauge[0].split()[-1]) == 0.0, gauge

    suite = h.suite()
    kp = suite.generate_keypair(b"faults-smoke")
    builder = TransactionBuilder(suite, None, chain_id=h.info["chain_id"],
                                 group_id=h.info["group_id"])
    sent = 0
    def burst(n):
        global sent
        for _ in range(n):
            tx = builder.build(kp, pc.BALANCE_ADDRESS,
                               pc.encode_call("register",
                                              lambda w: w.blob(b"s%d" % sent)
                                              .u64(1)),
                               nonce=f"s-{sent}", block_limit=500)
            h.client(sent % h.n).send_transaction(tx, wait=False)
            sent += 1
    burst(4)
    h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 2,
                 timeout=180, what="baseline commits")

    # one STORAGE failpoint + one CONSENSUS-pipeline failpoint, armed at
    # runtime over the ops endpoint, each firing a handful of times
    h.arm_failpoint(1, "storage.wal.append_before_fsync", "enospc*2")
    h.arm_failpoint(2, "scheduler.2pc.commit", "raise*2")
    burst(8)
    h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 8,
                 timeout=240, what="commits through the armed faults")
    height = h.wait_converged(range(h.n), min_height=1, timeout=240)
    for i in range(h.n):
        rep = h.audit_report(i)
        assert rep["ok"], (i, rep)
        fps = h.failpoints(i)
        assert "scheduler.2pc.commit" in fps["sites"], fps
    # every node back to ok (faults exhausted their budgets + self-healed)
    h.wait_until(lambda: all(h.healthz(i)[0] == 200 for i in range(h.n)),
                 timeout=120, what="health returned to ok on every node")
    print(f"sanitize_ci: FAULTS STAGE CLEAN (height={height}, "
          f"txs={min(h.total_txs(i) for i in range(h.n))})")
EOF
  exit 0
fi

if [ "${1:-}" = "--overload" ]; then
  echo "== [overload] brownout smoke: 4 real daemons, aggressor floods a" \
       "rate-limited edge while a polite client keeps committing;" \
       "-32005 observed, health returns to ok after the storm"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python - <<'EOF'
import tempfile, threading, time
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import RpcCallError, SdkClient, \
    TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

out = tempfile.mkdtemp(prefix="overload-smoke-")
STORM_S = 8.0
with ChaosHarness(out, tls=False,
                  config_overrides={"client_write_rate": 20.0,
                                    "txpool_limit": 2000}) as h:
    h.start_all()
    for i in range(h.n):
        h.wait_rpc_up(i)
    suite = h.suite()
    kp = suite.generate_keypair(b"overload-smoke")
    builder = TransactionBuilder(suite, None, chain_id=h.info["chain_id"],
                                 group_id=h.info["group_id"])
    port = h.info["nodes"][0]["rpc_port"]
    stop = threading.Event()
    stats = {"r32005": 0, "aggr_ok": 0, "pol_lat": [], "errors": []}

    def aggressor(w):
        sdk = SdkClient(f"http://127.0.0.1:{port}",
                        group=h.info["group_id"], api_key="aggr")
        i = 0
        while not stop.is_set():
            tx = builder.build(kp, pc.BALANCE_ADDRESS,
                               pc.encode_call("register",
                                              lambda w2: w2.blob(
                                                  b"ag%d-%d" % (w, i))
                                              .u64(1)),
                               nonce=f"ag-{w}-{i}", block_limit=500)
            i += 1
            try:
                sdk.send_transaction(tx, wait=False)
                stats["aggr_ok"] += 1
            except RpcCallError as exc:
                if exc.code == -32005:
                    stats["r32005"] += 1
            except Exception as exc:
                stats["errors"].append(f"aggr: {exc}")
                return

    def polite():
        sdk = SdkClient(f"http://127.0.0.1:{port}",
                        group=h.info["group_id"], api_key="polite",
                        timeout=30.0)
        i = 0
        while not stop.is_set():
            tx = builder.build(kp, pc.BALANCE_ADDRESS,
                               pc.encode_call("register",
                                              lambda w2: w2.blob(
                                                  b"po%d" % i).u64(1)),
                               nonce=f"po-{i}", block_limit=500)
            i += 1
            t0 = time.perf_counter()
            try:
                sdk.send_transaction(tx, wait=True)  # full commit RTT
                stats["pol_lat"].append(time.perf_counter() - t0)
            except Exception as exc:
                stats["errors"].append(f"polite: {exc}")
                return
            time.sleep(0.2)  # ~5/s: well inside its own budget

    threads = [threading.Thread(target=aggressor, args=(w,), daemon=True)
               for w in range(2)] + [threading.Thread(target=polite,
                                                      daemon=True)]
    for t in threads:
        t.start()
    time.sleep(STORM_S)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    assert not stats["errors"], stats["errors"][:3]
    lat = sorted(stats["pol_lat"])
    assert lat, "polite client never completed a commit"
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
    # the polite client's commits stay bounded THROUGH the storm
    assert p99 < 10.0, f"polite commit p99 {p99:.1f}s"
    assert stats["r32005"] > 0, "aggressor was never rate limited"
    # the overload/admission surfaces are live on the ops plane
    code, doc = h._ops_get(0, "/status")
    assert code == 200 and doc.get("admission"), doc.get("admission")
    assert doc["admission"]["rejected_writes"] > 0 or \
        doc["admission"]["rejected_fair_share"] > 0, doc["admission"]
    # after the storm: every node back to ok (busy cleared, nothing stuck)
    h.wait_until(lambda: all(
        h.healthz(i)[0] == 200 and h.healthz(i)[1]["state"] == "ok"
        for i in range(h.n)), timeout=120,
        what="health back to ok on every node")
    print(f"sanitize_ci: OVERLOAD STAGE CLEAN "
          f"(polite_p99={p99*1000:.0f}ms over {len(lat)} commits, "
          f"rate_limited={stats['r32005']}, "
          f"aggr_admitted={stats['aggr_ok']})")
EOF
  echo "== [overload] chain_bench --overload goodput/fairness rows"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --overload -n 600 \
    --overload-window 3 --overload-ab-runs 1 --overload-fairness-s 6 \
    --backend host 2>/dev/null | grep -E \
    '"metric": "overload_(goodput|fairness|seal_integrity)"'
  exit 0
fi

if [ "${1:-}" = "--zk" ]; then
  echo "== [zk] proof plane smoke: real daemons, getProof over RPC," \
       "client-side verification, tamper-detect, batched verifyProofs"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python - <<'EOF'
import tempfile
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import state_leaf_payload
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness
from fisco_bcos_tpu.zk import proof as zkproof


def unhex(s):
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


out = tempfile.mkdtemp(prefix="zk-smoke-")
with ChaosHarness(out, tls=False) as h:
    h.start_all()
    for i in range(h.n):
        h.wait_rpc_up(i)
    suite = h.suite()
    builder = TransactionBuilder(suite, None, chain_id=h.info["chain_id"],
                                 group_id=h.info["group_id"])
    kp = suite.generate_keypair(b"zk-smoke")
    sdk = h.client(0)
    # fire-and-forget so several txs share a block (multi-level proofs),
    # then poll receipts
    tx_hashes = []
    for i in range(6):
        tx = builder.build(kp, pc.BALANCE_ADDRESS,
                           pc.encode_call("register",
                                          lambda w, i=i: w.blob(
                                              b"zk%d" % i).u64(1 + i)),
                           nonce=f"zk-{i}", block_limit=500)
        r = sdk.send_transaction(tx, wait=False)
        tx_hashes.append(unhex(r["transactionHash"]))
    h.wait_until(lambda: all(
        sdk.get_transaction_receipt("0x" + th.hex()) is not None
        for th in tx_hashes), timeout=180, what="zk txs committed")
    group = h.info["group_id"]

    checked = 0
    for th in tx_hashes:
        doc = sdk.request("getProof", [group, "", "0x" + th.hex()])
        assert doc["found"], doc
        # anchor the roots to the node's committed header (the light
        # client would quorum-verify this header's seals; the in-repo
        # test suite covers that path over p2p)
        hdr = sdk.get_block_by_number(doc["blockNumber"], only_header=True)
        assert unhex(doc["txsRoot"]) == unhex(hdr["txsRoot"])
        assert unhex(doc["receiptsRoot"]) == unhex(hdr["receiptsRoot"])
        items = [(th, zkproof.w16_proof_from_json(doc["txProof"]),
                  unhex(doc["txsRoot"]))]
        ok = zkproof.verify_inclusion_batch(suite, items)
        assert ok.all(), "tx proof rejected"
        # tampered leaf / root / proof sibling must all reject
        leaf, proof, root = items[0]
        bad_leaf = bytes([leaf[0] ^ 1]) + leaf[1:]
        assert not zkproof.verify_inclusion_batch(
            suite, [(bad_leaf, proof, root)]).any()
        assert not zkproof.verify_inclusion_batch(
            suite, [(leaf, proof, b"\x05" * 32)]).any()
        if proof:
            sibs, pos = proof[0]
            forged = [([b"\x06" * 32] * len(sibs), pos)] + proof[1:]
            assert not zkproof.verify_inclusion_batch(
                suite, [(leaf, forged, root)]).any()
        checked += 1

    # batched verifyProofs: N good + 1 forged in ONE call
    docs = [sdk.request("getProof", [group, "", "0x" + th.hex()])
            for th in tx_hashes]
    proofs = [{"leaf": "0x" + th.hex(), "proof": d["txProof"],
               "root": d["txsRoot"]} for th, d in zip(tx_hashes, docs)]
    proofs.append({"leaf": "0x" + b"\x09".hex() * 32,
                   "proof": docs[0]["txProof"],
                   "root": docs[0]["txsRoot"]})
    res = sdk.request("verifyProofs", [group, "", proofs])
    assert res["results"][:-1] == [True] * len(tx_hashes), res
    assert res["results"][-1] is False
    assert res["verified"] == len(tx_hashes)

    # state proof: prove the head block's write of a c_balance row, with
    # the leaf recomputed client-side from the claimed value
    n = docs[-1]["blockNumber"] if docs else 1
    sp = sdk.request("getProof", [group])  # no-op shape check
    doc = sdk.request("getProof",
                      {"group": group, "number": n,
                       "state_keys": [["c_balance", "0x" + b"zk5".hex()]]})
    entry = doc["stateEntries"][0]
    assert entry["present"], entry
    value = (6).to_bytes(16, "big")  # register zk5 -> 1 + 5, 16-byte be
    leaf = suite.hash(state_leaf_payload("c_balance", b"zk5", value))
    assert leaf == unhex(entry["leafDigest"]), "state leaf mismatch"
    hdr = sdk.get_block_by_number(n, only_header=True)
    assert unhex(entry["stateRoot"]) == unhex(hdr["stateRoot"])
    ok = zkproof.verify_inclusion_batch(
        suite, [(leaf, zkproof.w16_proof_from_json(entry["stateProof"]),
                 unhex(entry["stateRoot"]))])
    assert ok.all(), "state proof rejected"
    # lying value -> different leaf -> rejected
    bad = suite.hash(state_leaf_payload("c_balance", b"zk5",
                                        (7).to_bytes(8, "big")))
    assert not zkproof.verify_inclusion_batch(
        suite, [(bad, zkproof.w16_proof_from_json(entry["stateProof"]),
                 unhex(entry["stateRoot"]))]).any()

    # the zk counters are live on the status plane
    code, st = h._ops_get(0, "/status")
    assert code == 200 and st.get("zk", {}).get("proofsVerified", 0) > 0, \
        st.get("zk")
    print(f"sanitize_ci: ZK STAGE CLEAN (proofs_checked={checked}, "
          f"verify_batch={res['verified']}+1neg, "
          f"zk_status={st['zk']})")
EOF
  echo "== [zk] chain_bench --proof-bench rows"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 900 \
    python benchmark/chain_bench.py --proof-bench --proof-txs 60 \
    --backend host 2>/dev/null | grep -E \
    '"metric": "(poseidon_hashes|proofs_(rendered|served|verified))_per_sec"'
  exit 0
fi

if [ "${1:-}" = "--gameday" ]; then
  echo "== [gameday] ci-smoke fault schedule on a real 4-node cluster:" \
       "kill -9 + asymmetric partition/heal + armed WAL-crash failpoint" \
       "under scenario load; clean audit, converged heads, health SLO," \
       "bounded write p99, byte-identical c_balance"
  GD_OUT="$(mktemp -d)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 15 1200 \
    python tools/gameday.py --schedule ci-smoke \
    -o "$GD_OUT/cluster" --report "$GD_OUT/report.json" \
    | tee "$GD_OUT/rows.jsonl"
  grep -q '"metric": "gameday_post_soak_tps"' "$GD_OUT/rows.jsonl"
  echo "== [gameday] perf gate, report-only, gameday_* rows vs trajectory"
  python tools/perf_gate.py --candidate "$GD_OUT/rows.jsonl" --report-only
  rm -rf "$GD_OUT"
  echo "sanitize_ci: GAMEDAY STAGE CLEAN"
  exit 0
fi

if [ "${1:-}" = "--chaos" ]; then
  echo "== [chaos] crash/fault e2e: kill -9 rejoin, leader view change," \
       "degraded link (4 OS processes, SM-TLS, real JSON-RPC)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" \
    python -m pytest tests/test_chaos_e2e.py -q -m slow -p no:cacheprovider
  echo "sanitize_ci: CHAOS STAGE CLEAN"
  exit 0
fi

# default full gate: the static/lint plane runs FIRST (cheapest, catches
# the most common regression class before any sanitizer rebuild)
run_lint_stage

LIBASAN="$(g++ -print-file-name=libasan.so)"
LIBTSAN="$(g++ -print-file-name=libtsan.so)"
LIBSTDCPP="$(g++ -print-file-name=libstdc++.so.6)"

echo "== [1/5] ASan+UBSan build (nevm, ncrypto, bcoskv)"
make -C native SANITIZE=address -j"$(nproc)"

echo "== [2/5] ASan+UBSan: native EVM + EC + storage suites"
# libstdc++ must ride LD_PRELOAD beside libasan: the EVM's C++ exceptions
# trip the __cxa_throw interceptor CHECK under dlopen otherwise (runtime
# artifact, not a library bug)
ASAN_OPTIONS=detect_leaks=0 \
  LD_PRELOAD="$LIBASAN $LIBSTDCPP" \
  FBTPU_NEVM_LIB=native/build/libnevm.asan.so \
  FBTPU_NCRYPTO_LIB=native/build/libncrypto.asan.so \
  FBTPU_BCOSKV_LIB=native/build/libbcoskv.asan.so \
  python -m pytest tests/test_nevm.py tests/test_nativeec.py \
      tests/test_native_storage.py -q -x

if [ "$FAST" = 0 ]; then
  echo "== [3/5] ASan+UBSan: deep differential fuzz (Python vs native EVM)"
  ASAN_OPTIONS=detect_leaks=0 \
    LD_PRELOAD="$LIBASAN $LIBSTDCPP" \
    FBTPU_NEVM_LIB=native/build/libnevm.asan.so \
    python -m pytest tests/test_nevm.py -q -x -m slow
else
  echo "== [3/5] SKIPPED (--fast): deep differential fuzz"
fi

echo "== [4/5] TSan build + native-storage race stress"
make -C native SANITIZE=thread -j"$(nproc)"
TSAN_OPTIONS="ignore_noninstrumented_modules=1" \
  LD_PRELOAD="$LIBTSAN $LIBSTDCPP" \
  FBTPU_BCOSKV_LIB=native/build/libbcoskv.tsan.so \
  python -m pytest tests/test_native_storage.py tests/test_race_stress.py \
      -q -x

echo "== [5/5] continuous-profiling smoke + perf gate (report-only)"
run_profile_stage

echo "sanitize_ci: ALL STAGES CLEAN"
