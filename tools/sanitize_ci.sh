#!/usr/bin/env bash
# One-command sanitizer + differential-fuzz gate for the native engines
# (VERDICT r4 #8; SURVEY §5 row 34 — the reference's
# cmake -DSANITIZE_ADDRESS/-DSANITIZE_THREAD CI jobs, cmake/Options.cmake:57).
#
#   tools/sanitize_ci.sh            # full gate: ASan+UBSan, TSan, fuzz
#   tools/sanitize_ci.sh --fast     # skip the @slow deep differential fuzz
#   tools/sanitize_ci.sh --chaos    # ONLY the multi-process fault gate:
#                                   # 4 OS-process TLS chain, kill -9 a node
#                                   # mid-stream, assert it rejoins to the
#                                   # same state root (tests/test_chaos_e2e)
#   tools/sanitize_ci.sh --ingest   # ONLY the continuous-batching smoke:
#                                   # short chain_bench --rpc-clients run,
#                                   # assert the lane coalesces (mean batch
#                                   # > 1) and emits an rpc_ingest_tps row
#
# Exit 0 = every stage clean. Each stage rebuilds the sanitizer variants
# from the CURRENT sources (the src-hash stamp keeps them honest) and runs
# the relevant suites with the sanitized libraries injected via the
# FBTPU_*_LIB loader seams.

set -euo pipefail
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

if [ "${1:-}" = "--ingest" ]; then
  echo "== [ingest] continuous-batching lane smoke: 4 HTTP clients," \
       "200 txs through the 4-node chain's ingest lane"
  OUT="$(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" timeout -k 10 600 \
    python benchmark/chain_bench.py --rpc-clients 4 -n 200 --backend host \
    2>/dev/null | grep '"metric": "rpc_ingest_tps"')"
  echo "$OUT"
  python - "$OUT" <<'EOF'
import json, sys
row = json.loads(sys.argv[1])
assert not row.get("timed_out"), f"chain wedged: {row}"
assert row["txs_committed"] >= 200, row
assert row["mean_batch"] > 1.0, f"lane not coalescing: {row}"
assert row["recover_calls_per_tx"] < 1.0, row
print("sanitize_ci: INGEST STAGE CLEAN "
      f"(tps={row['tps']}, mean_batch={row['mean_batch']}, "
      f"recover/tx={row['recover_calls_per_tx']})")
EOF
  exit 0
fi

if [ "${1:-}" = "--chaos" ]; then
  echo "== [chaos] crash/fault e2e: kill -9 rejoin, leader view change," \
       "degraded link (4 OS processes, SM-TLS, real JSON-RPC)"
  JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS="" \
    python -m pytest tests/test_chaos_e2e.py -q -m slow -p no:cacheprovider
  echo "sanitize_ci: CHAOS STAGE CLEAN"
  exit 0
fi

LIBASAN="$(g++ -print-file-name=libasan.so)"
LIBTSAN="$(g++ -print-file-name=libtsan.so)"
LIBSTDCPP="$(g++ -print-file-name=libstdc++.so.6)"

echo "== [1/4] ASan+UBSan build (nevm, ncrypto, bcoskv)"
make -C native SANITIZE=address -j"$(nproc)"

echo "== [2/4] ASan+UBSan: native EVM + EC + storage suites"
# libstdc++ must ride LD_PRELOAD beside libasan: the EVM's C++ exceptions
# trip the __cxa_throw interceptor CHECK under dlopen otherwise (runtime
# artifact, not a library bug)
ASAN_OPTIONS=detect_leaks=0 \
  LD_PRELOAD="$LIBASAN $LIBSTDCPP" \
  FBTPU_NEVM_LIB=native/build/libnevm.asan.so \
  FBTPU_NCRYPTO_LIB=native/build/libncrypto.asan.so \
  FBTPU_BCOSKV_LIB=native/build/libbcoskv.asan.so \
  python -m pytest tests/test_nevm.py tests/test_nativeec.py \
      tests/test_native_storage.py -q -x

if [ "$FAST" = 0 ]; then
  echo "== [3/4] ASan+UBSan: deep differential fuzz (Python vs native EVM)"
  ASAN_OPTIONS=detect_leaks=0 \
    LD_PRELOAD="$LIBASAN $LIBSTDCPP" \
    FBTPU_NEVM_LIB=native/build/libnevm.asan.so \
    python -m pytest tests/test_nevm.py -q -x -m slow
else
  echo "== [3/4] SKIPPED (--fast): deep differential fuzz"
fi

echo "== [4/4] TSan build + native-storage race stress"
make -C native SANITIZE=thread -j"$(nproc)"
TSAN_OPTIONS="ignore_noninstrumented_modules=1" \
  LD_PRELOAD="$LIBTSAN $LIBSTDCPP" \
  FBTPU_BCOSKV_LIB=native/build/libbcoskv.tsan.so \
  python -m pytest tests/test_native_storage.py tests/test_race_stress.py \
      -q -x

echo "sanitize_ci: ALL STAGES CLEAN"
