#!/usr/bin/env python3
"""Out-of-process execution workers smoke (sanitize_ci.sh --workers).

Boots a REAL 4-node PBFT cluster (OS processes, JSON-RPC) with
`[scheduler] workers = 1`, streams RPC writes, SIGKILLs one node's
execution worker MID-STREAM, and asserts the production contract:

  - the worker pool engaged (execWorkers in getSystemStatus, blocks > 0);
  - the kill is observed (bcos_exec_worker_deaths_total >= 1) and the
    scheduler restarts the worker via the health plane's respawn probe
    (new pid, alive, node health back to ok);
  - the chain never wedges: all writes commit, every node converges to
    the identical head hash, the c_balance table is byte-identical on
    every node (read back over RPC), and getAuditReport is clean.

Run directly (`python tools/workers_smoke.py`) or via the CI gate.
"""

import os
import signal
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from fisco_bcos_tpu.executor import precompiled as pc  # noqa: E402
from fisco_bcos_tpu.sdk.client import TransactionBuilder  # noqa: E402
from fisco_bcos_tpu.testing.chaos import ChaosHarness  # noqa: E402

N_PRE = 8     # writes committed before the kill
N_POST = 12   # writes streamed across/after the kill


def _exec_workers(h: ChaosHarness, i: int) -> dict:
    st = h.client(i).request("getSystemStatus", [h.info["group_id"], ""])
    ew = st.get("execWorkers")
    assert ew is not None, f"node {i} booted without an exec pool: {st}"
    return ew


def _deaths(h: ChaosHarness, i: int) -> float:
    for ln in h.metrics_text(i).splitlines():
        if ln.startswith("bcos_exec_worker_deaths_total"):
            return float(ln.split()[-1])
    return 0.0


def main() -> None:
    out = tempfile.mkdtemp(prefix="workers-smoke-")
    with ChaosHarness(out, tls=False,
                      config_overrides={"scheduler_workers": 1}) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)

        suite = h.suite()
        kp = suite.generate_keypair(b"workers-smoke")
        builder = TransactionBuilder(suite, None,
                                     chain_id=h.info["chain_id"],
                                     group_id=h.info["group_id"])
        sent = 0

        def burst(n):
            nonlocal sent
            for _ in range(n):
                tx = builder.build(
                    kp, pc.BALANCE_ADDRESS,
                    pc.encode_call("register",
                                   lambda w, s=sent: w.blob(b"wk%d" % s)
                                   .u64(100 + s)),
                    nonce=f"wk-{sent}", block_limit=500)
                h.client(sent % h.n).send_transaction(tx, wait=False)
                sent += 1

        # phase 1: the pool engages on every node
        burst(N_PRE)
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n))
                     >= N_PRE, timeout=240, what="pre-kill commits")
        ew0 = _exec_workers(h, 0)
        victim = ew0["per_worker"][0]["pid"]
        assert victim and ew0["per_worker"][0]["alive"], ew0
        assert sum(w["blocks"] for w in ew0["per_worker"]) >= 1, \
            f"pool never executed a block: {ew0}"

        # phase 2: SIGKILL node 0's worker MID-STREAM
        os.kill(victim, signal.SIGKILL)
        burst(N_POST)
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n))
                     >= N_PRE + N_POST, timeout=300,
                     what="commits through the worker kill")

        # the kill was OBSERVED and the health plane respawned the worker
        h.wait_until(lambda: _deaths(h, 0) >= 1, timeout=60,
                     what="worker death observed in metrics")
        h.wait_until(
            lambda: (lambda ew: ew["per_worker"][0]["alive"]
                     and ew["per_worker"][0]["pid"] != victim)
            (_exec_workers(h, 0)),
            timeout=120, what="health-plane respawn (new live pid)")
        h.wait_until(lambda: all(h.healthz(i)[0] == 200
                                 and h.healthz(i)[1]["state"] == "ok"
                                 for i in range(h.n)),
                     timeout=120, what="health back to ok on every node")

        # phase 3: the RESPAWNED worker executes real blocks
        burst(4)
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n))
                     >= sent, timeout=240, what="post-respawn commits")
        h.wait_until(
            lambda: sum(w["blocks"]
                        for w in _exec_workers(h, 0)["per_worker"]) >= 1,
            timeout=60, what="respawned worker executed a block")

        # phase 4: convergence — identical heads + byte-identical balances
        height = h.wait_converged(range(h.n), min_height=1, timeout=240)
        cli0 = h.client(0)
        heads = [h.client(i).request("getBlockHashByNumber",
                                     [h.info["group_id"], "", height])
                 for i in range(h.n)]
        assert len(set(heads)) == 1, heads

        def balances(i):
            cli = h.client(i)
            out = []
            for s in range(sent):
                call = pc.encode_call("balanceOf",
                                      lambda w, s=s: w.blob(b"wk%d" % s))
                r = cli.request("call", [h.info["group_id"], "",
                                         "0x" + pc.BALANCE_ADDRESS.hex(),
                                         "0x" + call.hex()])
                out.append(r["output"])
            return out

        want = balances(0)
        assert all(int(o[2:], 16) == 100 + s for s, o in enumerate(want)), \
            want
        for i in range(1, h.n):
            assert balances(i) == want, f"node {i} balance divergence"
        for i in range(h.n):
            rep = h.audit_report(i)
            assert rep["ok"], (i, rep)

        ew = _exec_workers(h, 0)
        print("workers_smoke: WORKERS STAGE CLEAN "
              f"(height={height}, txs={sent}, "
              f"deaths={_deaths(h, 0):.0f}, "
              f"fallbacks={ew['fallbacks']}, "
              f"pool_blocks={sum(w['blocks'] for w in ew['per_worker'])})")


if __name__ == "__main__":
    main()
