#!/usr/bin/env python3
"""perf_gate — noise-aware perf-regression gate over the bench trajectory.

The problem (PERF rounds 9/10/13, verbatim complaint): A/B medians on the
CI host flip sign inside a 1.45–1.6x run-to-run swing while /proc/loadavg
reads 0.00 — so a naive "candidate < last run ⇒ regression" gate would be
red half the time and trusted never. This gate makes the comparison the
way the repo's own PERF methodology demands:

  * the REFERENCE for each metric is the median of the recorded
    trajectory (`BENCH_r*.json` `parsed` lines) plus the `chain` section
    of `BENCH_LAST_GOOD.json` (when present);
  * the TOLERANCE BAND per metric is derived from the recorded run
    SPREAD of that very metric across the trajectory — a metric that
    historically swings 1.4x gets a wide band, a stable one gets the
    floor band — capped so a true 2x regression can never hide;
  * the HOST-WEATHER stamp (analysis/hostweather.py) on the candidate
    row, and a fresh sample taken by the gate itself, WIDEN the band on
    a noisy host instead of silently failing honest runs;
  * MULTIPLE candidate files are reduced to per-metric medians
    (interleaved A/B runs), and metrics with fewer than `--min-runs`
    recorded observations are ADVISORY (reported, never fatal).

Exit 0 = no enforced regression (or --report-only). Exit 1 = at least one
enforced metric fell below its band. Exit 2 = usage/input error.

Usage:
  tools/perf_gate.py --candidate BENCH_NEW.json [--candidate ...]
  bench.py ... | tools/perf_gate.py --candidate - --report-only
  tools/perf_gate.py --candidate X.json --update-last-good   # record the
      passing candidate's chain metrics into BENCH_LAST_GOOD.json[chain]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# band parameters (fractions of the reference median)
MIN_BAND = 0.12    # floor: even a historically flat metric gets this
SPREAD_K = 0.75    # band contribution per unit of relative spread
MAX_BAND = 0.45    # cap: a 2x regression (cand = 0.5*ref) must ALWAYS trip
NOISE_MARGIN = 0.10  # extra width when the host weather says "co-tenant"
# a drop past this ratio is fatal even for advisory (<min-runs) metrics:
# with MAX_BAND at 0.45, 0.52 keeps an injected 2x regression (ratio 0.5)
# caught no matter how thin the metric's recorded history is
CATASTROPHIC = 0.52

# metric-name heuristics: which numeric fields of a bench line are
# comparable performance numbers, and in which direction
_HIGHER_SUFFIXES = ("_tps", "_qps", "_per_sec", "_speedup", "_share")
_HIGHER_EXACT = {"value", "vs_baseline", "recover_vs_baseline",
                 "chain_tps_4node_host", "pipeline_tps", "rpc_ingest_tps",
                 "rpc_read_qps", "groups_scaling_2x", "groups_tps_median",
                 "recover_sigs_per_sec", "native_host_floor_sigs_per_sec",
                 "replay_blocks_per_sec", "poseidon_hashes_per_sec",
                 "rpc_read_cache_hit_rate",
                 # columnar wire ingest vs the object path (adjacent-pair
                 # ratio median) and the exec pools' busy fraction over
                 # the timed window — both shrink when the substrate or
                 # the worker seam regresses
                 "columnar_vs_object", "exec_worker_occupancy"}
_LOWER_SUFFIXES = ("_ms", "_seconds", "_mb", "_cost_pct", "_ns")
# lower-is-better fields whose names don't carry a _LOWER suffix: the
# sealer's idle threading-wait share of attributed CPU (the event-driven
# sealer's acceptance number — PR 16 measured 15.4% under the 0.02 s poll)
_LOWER_EXACT = {"seal_wait_share_pct",
                # push-plane acceptance numbers (PR 20): commit->client
                # notify tail (also caught by the _ms suffix — pinned
                # here so a rename can't silently un-gate it) and the
                # fan-out CPU burned per delivered notification (the
                # zero-extra-render contract: flat as subscribers grow)
                "sub_notify_p99_ms", "sub_cpu_us_per_notify"}
_SKIP = {"cpu_cores", "rpc_ingest_clients", "rpc_read_clients",
         "sub_subscribers",
         "poseidon_batch", "overload_rate_limited", "live_value",
         "cpu_baseline_sigs_per_sec", "spin_score", "sampled_at",
         "measured_at",
         # run-size / config-dependent absolutes: these scale with the
         # run's CLI args (-n, client counts, memtable knobs), so pooling
         # them across runs would gate the CONFIG, not the code — a
         # doubled -n must never read as a catastrophic wall_seconds
         # regression
         "wall_seconds", "submit_seconds", "episode_seconds",
         "join_seconds", "cross_shard_drain_seconds",
         "dataset_mb", "disk_dataset_mb", "memtable_mb",
         "peak_rss_mb", "peak_rss_disk_mb", "peak_rss_memory_mb",
         "storage_peak_rss_disk_mb",
         "cpu_seconds", "attributed_cpu_seconds", "profiler_cpu_seconds",
         # counts that scale with the run's -n / worker config, and the
         # fallback counter whose healthy median is exactly 0 (ratio
         # banding around zero is meaningless; workers_smoke asserts the
         # fallback/respawn contract directly)
         "exec_worker_pool_blocks", "exec_worker_fallbacks", "workers",
         "pool_blocks", "exec_fallbacks",
         # commit-seal carriage observability (--seal-bench /
         # --trace-profile summary): these pool across seal_mode and
         # roster size under one name, so a cert-mode run would gate
         # against an aggregate-mode median (239 vs 95 bytes is config,
         # not code). tests/test_qc.py pins the cert<multi<aggregate byte
         # ordering deterministically; the gated consensus numbers are
         # consensus_pre_ms / consensus_wait_ms on the summary row
         "seal_bytes_per_block", "vs_multi", "span_verify_ms",
         "sealers", "quorum"}


def direction(metric: str):
    """'higher' | 'lower' | None (not gated). Accepts both bare field
    names and metric-qualified ones (`<metric>.<field>`)."""
    base = metric.rsplit(".", 1)[-1]
    if base in _SKIP or base.startswith("host_weather"):
        return None
    if base in _HIGHER_EXACT or base.endswith(_HIGHER_SUFFIXES):
        return "higher"
    if base in _LOWER_EXACT or base.endswith(_LOWER_SUFFIXES):
        return "lower"
    return None


# fields whose MEANING depends on the row's `metric` identity (the
# headline `value` is sigs/sec at whatever batch size that run used —
# pooling value@1024 with value@65536 would make the reference median
# nonsense, which the recorded trajectory actually demonstrates:
# r02=47194 @16k, r03=50.9 @1k-CPU-fallback, r04=95022 @64k)
_METRIC_SCOPED = {"value", "vs_baseline", "recover_vs_baseline"}


def flatten(line: dict) -> dict[str, float]:
    """Bench line -> {metric: float} for every gateable numeric field.
    Generic fields are qualified by the row's `metric` name so only
    like-for-like observations ever share a reference."""
    out = {}
    ident = str(line.get("metric", ""))
    for k, v in line.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if direction(k) is None:
            continue
        out[f"{ident}.{k}" if k in _METRIC_SCOPED and ident else k] = \
            float(v)
    return out


def load_history(pattern: str) -> tuple[list[dict], list[int]]:
    """-> (parsed bench lines oldest-first, best spin_scores seen)."""
    lines, spins = [], []
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        if isinstance(rec, dict) and parsed is None and "metric" in rec:
            parsed = rec  # a bare bench line is also accepted as history
        if isinstance(parsed, dict):
            lines.append(parsed)
            spin = (parsed.get("host_weather") or {}).get("spin_score")
            if isinstance(spin, (int, float)):
                spins.append(int(spin))
    return lines, spins


def load_last_good(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def load_candidates(paths: list[str]) -> list[dict]:
    cands = []
    for p in paths:
        try:
            text = sys.stdin.read() if p == "-" else open(p).read()
        except OSError as exc:
            raise SystemExit(f"perf_gate: cannot read candidate {p}: {exc}")
        # a whole-file JSON document: a bare bench line, a BENCH_rNN
        # wrapper ({.., "parsed": line}), or a list of lines
        try:
            doc = json.loads(text)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            parsed = doc.get("parsed", doc)
            if isinstance(parsed, dict) and "metric" in parsed:
                cands.append(parsed)
                continue
        if isinstance(doc, list):
            cands.extend(d for d in doc
                         if isinstance(d, dict) and "metric" in d)
            continue
        # else: a bench.py stdout stream — one JSON line per row
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue
            if isinstance(row, dict) and "metric" in row:
                cands.append(row)
    if not cands:
        raise SystemExit("perf_gate: no parseable bench line in candidates")
    return cands


def gate(candidates: list[dict], history: list[dict], last_good: dict,
         min_runs: int = 3, weather_now: dict | None = None,
         best_spin: int | None = None) -> dict:
    """Pure comparison (importable for tests): -> report dict with
    per-metric verdicts and an overall `ok`."""
    from fisco_bcos_tpu.analysis import hostweather

    # candidate medians across (interleaved) runs
    cand_vals: dict[str, list[float]] = {}
    for line in candidates:
        for m, v in flatten(line).items():
            cand_vals.setdefault(m, []).append(v)
    cand = {m: statistics.median(vs) for m, vs in cand_vals.items()}

    hist_vals: dict[str, list[float]] = {}
    for line in history:
        for m, v in flatten(line).items():
            hist_vals.setdefault(m, []).append(v)
    chain_lg = (last_good.get("chain") or {})
    for m, rec in chain_lg.items():
        v = rec.get("value") if isinstance(rec, dict) else rec
        if isinstance(v, (int, float)) and direction(m) is not None:
            hist_vals.setdefault(m, []).append(float(v))

    # host weather: candidate stamps + the gate's own fresh sample
    noisy_reasons = []
    for line in candidates:
        is_noisy, why = hostweather.noisy(line.get("host_weather"),
                                          best_spin)
        if is_noisy:
            noisy_reasons.append(f"candidate: {why}")
            break
    if weather_now is not None:
        is_noisy, why = hostweather.noisy(weather_now, best_spin)
        if is_noisy:
            noisy_reasons.append(f"gate-time: {why}")
    margin = NOISE_MARGIN if noisy_reasons else 0.0

    rows = []
    failed = []
    for m, cv in sorted(cand.items()):
        hv = hist_vals.get(m, [])
        if not hv:
            rows.append({"metric": m, "candidate": cv, "verdict": "new",
                         "note": "no recorded reference"})
            continue
        ref = statistics.median(hv)
        if ref == 0:
            continue
        spread = (max(hv) - min(hv)) / abs(ref) if len(hv) >= 2 else 0.0
        band = min(MAX_BAND, max(MIN_BAND, SPREAD_K * spread) + margin)
        d = direction(m)
        ratio = cv / ref
        if d == "higher":
            bad = ratio < (1.0 - band)
            good = ratio > (1.0 + band)
        else:
            bad = ratio > (1.0 + band)
            good = ratio < (1.0 - band)
        advisory = len(hv) < min_runs
        # catastrophic drops are fatal regardless of history depth: noise
        # tolerance exists for marginal calls, not for a halved metric
        catastrophic = (ratio <= CATASTROPHIC if d == "higher"
                        else ratio >= 1.0 / CATASTROPHIC)
        verdict = ("regression" if bad else
                   "improved" if good else "ok")
        if bad and (not advisory or catastrophic):
            failed.append(m)
            advisory = advisory and not catastrophic
        rows.append({
            "metric": m, "direction": d,
            "candidate": cv, "reference": round(ref, 3),
            "ratio": round(ratio, 3), "band": round(band, 3),
            "runs_recorded": len(hv), "advisory": advisory,
            "verdict": verdict + ("(advisory)" if advisory and bad else ""),
        })
    return {
        "ok": not failed,
        "failed": failed,
        "noisy": noisy_reasons,
        "band_margin": margin,
        "candidate_runs": len(candidates),
        "rows": rows,
    }


def update_last_good(path: str, candidates: list[dict]) -> None:
    """Record the passing candidate's chain-level medians into
    BENCH_LAST_GOOD.json under `chain` (read-modify-write via bench.py's
    locked helper when importable, plain rewrite otherwise)."""
    import time as _time
    cand_vals: dict[str, list[float]] = {}
    for line in candidates:
        for m, v in flatten(line).items():
            cand_vals.setdefault(m, []).append(v)
    ts = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    rec = load_last_good(path)
    chain = rec.setdefault("chain", {})
    for m, vs in cand_vals.items():
        chain[m] = {"value": round(statistics.median(vs), 3),
                    "runs": len(vs), "measured_at": ts}
    rec["updated_at"] = ts
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def print_report(rep: dict, out=sys.stdout) -> None:
    w = max([len(r["metric"]) for r in rep["rows"]] + [8])
    print(f"perf_gate: {rep['candidate_runs']} candidate run(s), "
          f"band margin +{rep['band_margin']:.0%} "
          f"({'; '.join(rep['noisy']) or 'host quiet'})", file=out)
    for r in rep["rows"]:
        if r["verdict"] == "new":
            print(f"  {r['metric']:<{w}}  {r['candidate']:>12}  NEW "
                  f"(no reference)", file=out)
            continue
        mark = {"ok": " ", "improved": "+",
                "regression": "!"}.get(r["verdict"].split("(")[0], "?")
        print(f"{mark} {r['metric']:<{w}}  {r['candidate']:>12} vs "
              f"{r['reference']:>12}  x{r['ratio']:<6} "
              f"band ±{r['band']:.0%} ({r['runs_recorded']} runs"
              f"{', advisory' if r['advisory'] else ''})  {r['verdict']}",
              file=out)
    print(f"perf_gate: {'PASS' if rep['ok'] else 'FAIL'}"
          + (f" — regressions: {', '.join(rep['failed'])}"
             if rep["failed"] else ""), file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--candidate", action="append", required=True,
                    metavar="FILE", help="bench line JSON (repeatable; "
                    "'-' reads stdin; files may hold several lines — "
                    "medians are taken per metric)")
    ap.add_argument("--history", default=os.path.join(_REPO,
                                                      "BENCH_r*.json"),
                    help="trajectory glob (default: repo BENCH_r*.json)")
    ap.add_argument("--last-good",
                    default=os.path.join(_REPO, "BENCH_LAST_GOOD.json"))
    ap.add_argument("--min-runs", type=int, default=3,
                    help="recorded observations below this make a metric "
                         "advisory (reported, never fatal)")
    ap.add_argument("--report-only", action="store_true",
                    help="always exit 0 (the trajectory-watch mode)")
    ap.add_argument("--no-weather", action="store_true",
                    help="skip the gate-time host-weather sample")
    ap.add_argument("--update-last-good", action="store_true",
                    help="on PASS, record candidate chain medians into "
                         "BENCH_LAST_GOOD.json[chain]")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    args = ap.parse_args(argv)

    from fisco_bcos_tpu.analysis import hostweather

    candidates = load_candidates(args.candidate)
    history, spins = load_history(args.history)
    last_good = load_last_good(args.last_good)
    weather_now = None if args.no_weather else hostweather.sample()
    rep = gate(candidates, history, last_good, min_runs=args.min_runs,
               weather_now=weather_now, best_spin=max(spins, default=None))
    if args.json:
        print(json.dumps(rep, indent=1))
    else:
        print_report(rep)
    if rep["ok"] and args.update_last_good:
        update_last_good(args.last_good, candidates)
        print(f"perf_gate: chain medians recorded into {args.last_good}")
    if args.report_only:
        return 0
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
