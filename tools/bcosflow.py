#!/usr/bin/env python3
"""bcosflow — whole-program plane-contract analyzer for fisco_bcos_tpu.

bcoslint (tools/bcoslint.py) checks invariants a single function can
violate *lexically*; this tool checks the ones that live BETWEEN
functions: a blocking send hidden one call deep under a hot lock, a
lock-order inversion split across two modules, an fsync edge whose
failpoint arming lives in the caller, a host↔device sync buried in a
kernel the crypto-lane dispatcher reaches through four layers.

It builds a whole-repo call graph over `fisco_bcos_tpu/` (AST-based name
resolution: module defs, methods via self/cls + constructor-site receiver
typing, `functools.partial` and `threading.Thread(target=...)` edges),
classifies thread roots into execution planes (analysis/profiler's
thread-role registry + analysis/planes.py), propagates per-function
effect summaries transitively, and enforces the plane contracts declared
in fisco_bcos_tpu/analysis/planes.py.

Passes (rule ids):
    plane-blocking        blocking effect (lockorder.BLOCKING_KINDS)
                          reachable from a plane root whose contract
                          forbids that kind
    lock-blocking-interproc
                          blocking effect reachable from UNDER a HOT lock
                          (lockorder.HOT_LOCKS) across >=1 call boundary
                          (the lexical depth-0 case is bcoslint's)
    lock-order-interproc  a ranked lock acquired while a higher-or-equal
                          ranked lock is held, across call boundaries
                          (analysis/lockorder.RANK)
    fsync-path-unarmed    a storage/snapshot durability edge (fsync /
                          os.replace) where NO function on some root->site
                          call path crosses a failpoint — the kill -9
                          matrix cannot reach it (whole-program version of
                          bcoslint's per-function rule: a caller that arms
                          the site satisfies this one)
    lane-host-sync        block_until_ready / np.asarray / .item()
                          host-sync reachable from the crypto-lane
                          dispatcher OUTSIDE the sanctioned demux boundary
                          (planes.LANE_SYNC_BOUNDARY)
    jit-impure            blocking / host-sync / print effects inside a
                          jit-decorated function (host syncs break the
                          trace; effects silently run once at trace time)
    jit-shape-branch      `if ...shape...` branching inside a jit body —
                          one compile PER SHAPE; route through the padding
                          buckets instead
    hot-loop-alloc        per-item Python object construction in a loop
                          reachable from the wire->lane->seal hot path
                          (guard rail for the ROADMAP-1 columnar refactor)

Usage:
    python tools/bcosflow.py                  # analyze vs baseline
    python tools/bcosflow.py --json           # findings as JSON
    python tools/bcosflow.py --graph out.json # dump the call graph
    python tools/bcosflow.py --no-baseline    # show EVERY finding
    python tools/bcosflow.py --update-baseline
    python tools/bcosflow.py --changed-only   # git-diff-scoped report
    python tools/bcosflow.py --stats          # resolution/timing only

Suppression (same line or the line directly above the effect):
    something()  # bcosflow: disable=plane-blocking
    # bcosflow: disable=all

Baseline: tools/bcosflow_baseline.txt, same rule|path|scope|fingerprint|
justification format as bcoslint's (entries survive line churn; stale
ones are warned about and pruned by --update-baseline).

The analyzer imports NOTHING from the package (lockorder/planes/profiler
are loaded by file path) — it must never pay for, or require, a jax
import, and it must finish inside the CI lint budget (<30 s).
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import importlib.util
import json
import os
import re
import subprocess
import sys
import time
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "fisco_bcos_tpu"
DEFAULT_BASELINE = os.path.join(REPO, "tools", "bcosflow_baseline.txt")
DEFAULT_CACHE = os.path.join(REPO, "tools", ".bcosflow_cache.json")
# cache version = hash of this very file: ANY analyzer change invalidates
# every cached module summary (stale summaries silently change findings)
try:
    with open(os.path.abspath(__file__), "rb") as _f:
        SUMMARY_VERSION = hashlib.sha1(_f.read()).hexdigest()[:16]
except OSError:
    SUMMARY_VERSION = "unknown"

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import bcoslint  # noqa: E402 — shared Violation/baseline/file-walk infra


def _load_by_path(name: str, rel: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, PKG, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


lockorder = bcoslint.lockorder
planes = _load_by_path("_bcosflow_planes", "analysis/planes.py")
profiler = _load_by_path("_bcosflow_profiler", "analysis/profiler.py")

SUPPRESS_RE = re.compile(r"#\s*bcosflow:\s*disable=([a-z\-,\s]+|all)")

# call-site attr -> blocking kind (bcoslint's vocabulary + poseidon)
BLOCKING_ATTRS = {
    "fsync": "fsync", "fdatasync": "fsync",
    "sendall": "socket_send", "send_text": "socket_send",
    "send_binary": "socket_send",
    "verify_batch": "suite_batch", "recover_batch": "suite_batch",
    "hash_batch": "suite_batch", "poseidon_batch": "suite_batch",
}
SUBPROCESS_ATTRS = {"run", "check_call", "check_output", "call", "Popen"}
HOST_SYNC_ATTRS = {"block_until_ready", "device_get", "item"}
NP_SYNC_FUNCS = {"asarray", "array", "concatenate", "stack", "copy"}
ALLOC_ATTRS = {"from_bytes", "from_json", "from_dict"}
FSYNC_FP_SCOPE = ("fisco_bcos_tpu/storage/", "fisco_bcos_tpu/snapshot/")

# CHA-by-name fallback: method names too generic to attribute to a repo
# class when the receiver is untyped (indistinguishable from stdlib) are
# excluded from resolution entirely — neither edges nor the stat's
# denominator. Typed receivers resolve them normally.
GENERIC_NAMES = frozenset({
    "get", "put", "set", "add", "pop", "push", "clear", "copy", "update",
    "start", "stop", "close", "run", "join", "send", "recv", "read",
    "write", "append", "appendleft", "popleft", "extend", "insert",
    "remove", "discard", "count", "index", "sort", "reverse", "keys",
    "values", "items", "encode", "decode", "split", "strip", "replace",
    "format", "lower", "upper", "hex", "digest", "name", "wait",
    "notify", "notify_all", "acquire", "release", "submit", "shutdown",
    "exists", "flush", "fileno", "accept", "connect", "bind", "listen",
    "setdefault", "render", "load", "loads", "dump", "dumps", "commit",
    "prepare", "rollback", "begin", "info", "debug", "warning", "error",
    "exception", "critical", "call", "cancel", "result", "done", "next",
    "hash", "sign", "verify", "seal", "reset", "match", "search", "group",
})
CHA_CAP = 6  # max same-name candidates a nameless receiver may fan to

_GENERIC_SKIPPED = 0  # module-level counter for the stats line


# ---------------------------------------------------------------------------
# per-module extraction (pure-data summaries; JSON-cacheable)
# ---------------------------------------------------------------------------

def _mod_name(relpath: str) -> str:
    """fisco_bcos_tpu/rpc/edge.py -> rpc.edge ; .../zk/__init__.py -> zk"""
    p = relpath
    if p.startswith(PKG + "/"):
        p = p[len(PKG) + 1:]
    if p.endswith("/__init__.py"):
        return p[:-len("/__init__.py")].replace("/", ".")
    if p == "__init__.py":
        return "<root>"
    return p[:-3].replace("/", ".")


def _dotted(expr: ast.expr) -> Optional[str]:
    """Name / dotted Attribute chain -> 'a.b.c' (None otherwise)."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _name_prefix(expr: Optional[ast.expr]) -> Optional[str]:
    """Literal (prefix of a) thread name: Constant or leading JoinedStr
    constant."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr) and expr.values and \
            isinstance(expr.values[0], ast.Constant):
        return str(expr.values[0].value)
    return None


class _ModuleExtract:
    """One file -> a JSON-serializable summary: defs with their calls,
    effects, lock acquisitions; class layouts; import map."""

    def __init__(self, src: str, relpath: str):
        self.relpath = relpath
        self.module = _mod_name(relpath)
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=relpath)
        self.is_pkg = relpath.endswith("__init__.py")
        self.imports: dict[str, list] = {}   # name -> ["mod"|"sym"|"ext",..]
        self.classes: dict[str, dict] = {}   # name -> {bases, methods,
        #                                      attr_types, lock_attrs}
        self.funcs: dict[str, dict] = {}     # qual -> summary
        self.suppress: dict[int, str] = {}   # line -> rules string
        self._mod_lock_attrs = {}
        for suffix, attrs in lockorder.MODULE_LOCK_ATTRS.items():
            if relpath.endswith(suffix):
                self._mod_lock_attrs = attrs
                break
        for i, ln in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(ln)
            if m:
                self.suppress[i] = m.group(1).strip()
        self._collect_imports()
        self._collect_classes()
        self._collect_funcs()

    # -- imports -----------------------------------------------------------
    def _rel_base(self, level: int) -> str:
        parts = self.module.split(".") if self.module != "<root>" else []
        if not self.is_pkg:
            parts = parts[:-1]
        if level > 1:
            parts = parts[: len(parts) - (level - 1)]
        return ".".join(parts)

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    self.imports[name] = ["ext", a.name]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                if node.level > 0:
                    base = self._rel_base(node.level)
                    mod = f"{base}.{node.module}" if base and node.module \
                        else (node.module or base)
                elif node.module and (node.module == PKG
                                      or node.module.startswith(PKG + ".")):
                    mod = node.module[len(PKG) + 1:] or "<root>"
                else:
                    for a in node.names:
                        self.imports[a.asname or a.name] = \
                            ["ext", node.module or "?"]
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = ["sym", mod, a.name]

    def _class_ref(self, expr: ast.expr) -> Optional[str]:
        """Resolve an expression naming a class/function to a dotted repo
        ref ('module.Sym'), via the import map or same-module defs."""
        d = _dotted(expr)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        imp = self.imports.get(head)
        if imp is None:
            return f"{self.module}.{d}"  # same-module name
        if imp[0] == "sym":
            base = f"{imp[1]}.{imp[2]}"
            return f"{base}.{rest}" if rest else base
        if imp[0] == "ext":
            return None
        return None

    # -- classes -----------------------------------------------------------
    def _collect_classes(self) -> None:
        for node in self.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            info = {"bases": [], "methods": [], "attr_types": {},
                    "lock_attrs": dict(self._mod_lock_attrs), "line":
                    node.lineno}
            for b in node.bases:
                ref = self._class_ref(b)
                if ref:
                    info["bases"].append(ref)
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    info["methods"].append(stmt.name)
            self.classes[node.name] = info
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._scan_attr_assigns(node.name, stmt, info)

    def _scan_attr_assigns(self, cls: str, fn: ast.FunctionDef,
                           info: dict) -> None:
        ann = {a.arg: self._class_ref(a.annotation)
               for a in fn.args.args if a.annotation is not None}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            attr, val = t.attr, node.value
            if isinstance(val, ast.Call):
                d = _dotted(val.func)
                if d in ("lc.make_lock", "lc.make_rlock",
                         "lc.make_condition", "_lc.make_lock",
                         "_lc.make_rlock", "_lc.make_condition",
                         "lockcheck.make_lock", "lockcheck.make_rlock",
                         "lockcheck.make_condition") and val.args and \
                        isinstance(val.args[0], ast.Constant):
                    info["lock_attrs"].setdefault(attr, val.args[0].value)
                    continue
                if d in ("threading.Lock", "threading.RLock",
                         "threading.Condition"):
                    info["lock_attrs"].setdefault(
                        attr, f"raw:{self.module}.{attr}")
                    continue
                ref = self._class_ref(val.func)
                if ref and ref.rsplit(".", 1)[-1][:1].isupper():
                    info["attr_types"].setdefault(attr, ref)
            elif isinstance(val, ast.Name) and val.id in ann and \
                    fn.name == "__init__" and ann[val.id]:
                info["attr_types"].setdefault(attr, ann[val.id])

    # -- function bodies ---------------------------------------------------
    def _collect_funcs(self) -> None:
        def walk(body, qual_prefix, cls):
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = f"{qual_prefix}.{node.name}" if qual_prefix \
                        else f"{self.module}.{node.name}"
                    self._extract_func(node, qual, cls)
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, f"{self.module}.{node.name}",
                         node.name)
        walk(self.tree.body, "", None)

    def _jit_decorated(self, fn: ast.FunctionDef) -> tuple[bool, list]:
        """-> (is_jit, static arg names/indices)."""
        for dec in fn.decorator_list:
            d = _dotted(dec) or ""
            if d.endswith("jax.jit") or d == "jit":
                return True, []
            if isinstance(dec, ast.Call):
                dc = _dotted(dec.func) or ""
                if dc.endswith("partial") and dec.args and \
                        (_dotted(dec.args[0]) or "").endswith("jit"):
                    static = []
                    for kw in dec.keywords:
                        if kw.arg == "static_argnums":
                            static += [e.value for e in ast.walk(kw.value)
                                       if isinstance(e, ast.Constant)]
                        elif kw.arg == "static_argnames":
                            static += [e.value for e in ast.walk(kw.value)
                                       if isinstance(e, ast.Constant)]
                    return True, static
                if dc.endswith("jax.jit") or dc == "jit":
                    return True, []
        return False, []

    def _extract_func(self, fn: ast.FunctionDef, qual: str,
                      cls: Optional[str]) -> None:
        is_jit, jit_static = self._jit_decorated(fn)
        params = [a.arg for a in fn.args.args]
        static_params = {params[i] for i in jit_static
                         if isinstance(i, int) and i < len(params)}
        static_params |= {s for s in jit_static if isinstance(s, str)}
        rec = {"qual": qual, "module": self.module, "cls": cls,
               "name": fn.name, "line": fn.lineno, "path": self.relpath,
               "jit": is_jit, "jit_static": sorted(static_params),
               "fp_armed": False, "calls": [], "effects": [],
               "acquires": [], "params": params,
               "is_ctor": fn.name == "__init__"}
        self.funcs[qual] = rec
        cinfo = self.classes.get(cls, {})
        lock_attrs = cinfo.get("lock_attrs", self._mod_lock_attrs)
        attr_types = cinfo.get("attr_types", {})
        local_defs: set[str] = set()
        for st in fn.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.add(st.name)
        var_types = {a.arg: self._class_ref(a.annotation)
                     for a in fn.args.args if a.annotation is not None}
        var_types = {k: v for k, v in var_types.items() if v}

        def text(line: int) -> str:
            return self.lines[line - 1].strip() \
                if 1 <= line <= len(self.lines) else ""

        def lockname_of(expr: ast.expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls"):
                return lock_attrs.get(expr.attr)
            if isinstance(expr, ast.Attribute):
                return lock_attrs.get(expr.attr)
            return None

        def effect(cat, kind, what, line, locks, loop=0):
            rec["effects"].append(
                {"cat": cat, "kind": kind, "what": what, "line": line,
                 "locks": list(locks), "loop": loop, "text": text(line)})

        def call_desc(node: ast.Call, locks, loop):
            f = node.func
            desc = None
            if isinstance(f, ast.Name):
                n = f.id
                if n in local_defs:
                    desc = {"t": "qual",
                            "q": f"{qual}.<locals>.{n}", "name": n}
                else:
                    imp = self.imports.get(n)
                    if imp is None:
                        desc = {"t": "bare", "name": n}
                    elif imp[0] == "sym":
                        desc = {"t": "symbol", "mod": imp[1],
                                "name": imp[2]}
                    else:
                        desc = {"t": "ext", "mod": imp[1], "attr": n}
            elif isinstance(f, ast.Attribute):
                attr = f.attr
                base = f.value
                if isinstance(base, ast.Name):
                    b = base.id
                    if b in ("self", "cls"):
                        desc = {"t": "self", "attr": attr}
                    elif b in var_types:
                        desc = {"t": "typed", "cls": var_types[b],
                                "attr": attr}
                    elif b in self.imports:
                        imp = self.imports[b]
                        if imp[0] == "ext":
                            desc = {"t": "ext", "mod": imp[1],
                                    "attr": attr}
                        elif imp[0] == "sym":
                            desc = {"t": "typed",
                                    "cls": f"{imp[1]}.{imp[2]}",
                                    "attr": attr}
                        else:
                            desc = {"t": "modfunc", "mod": imp[1],
                                    "name": attr}
                    else:
                        desc = {"t": "unknown", "attr": attr}
                elif isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id in ("self", "cls"):
                    at = attr_types.get(base.attr)
                    if at:
                        desc = {"t": "typed", "cls": at, "attr": attr}
                    else:
                        lk = lock_attrs.get(base.attr)
                        desc = {"t": "unknown", "attr": attr,
                                "recv_lock": lk}
                elif isinstance(base, ast.Call):
                    d = _dotted(base.func) or ""
                    if d.endswith("super"):
                        desc = {"t": "super", "attr": attr, "cls": cls}
                    else:
                        desc = {"t": "unknown", "attr": attr}
                else:
                    desc = {"t": "unknown", "attr": attr}
            else:
                return  # call of a call / subscript — opaque
            desc["line"] = node.lineno
            desc["locks"] = list(locks)

            dd = _dotted(f) or ""
            # -- effects at the call site ---------------------------------
            attr = desc.get("attr") or desc.get("name") or ""
            if attr in BLOCKING_ATTRS:
                effect("blocking", BLOCKING_ATTRS[attr], dd or attr,
                       node.lineno, locks)
            elif dd == "time.sleep":
                effect("blocking", "sleep", dd, node.lineno, locks)
            elif dd == "os.replace":
                effect("blocking", "fsync", dd, node.lineno, locks)
            elif dd.startswith("subprocess.") and \
                    dd.split(".")[-1] in SUBPROCESS_ATTRS:
                effect("blocking", "subprocess", dd, node.lineno, locks)
            elif attr == "note_blocking" and node.args and \
                    isinstance(node.args[0], ast.Constant):
                effect("blocking", node.args[0].value,
                       "note_blocking marker", node.lineno, locks)
            if attr in ("fire", "fire_lossy", "_maybe_fail"):
                rec["fp_armed"] = True
            if attr in HOST_SYNC_ATTRS:
                effect("host_sync", attr, dd or attr, node.lineno, locks)
            elif desc.get("t") == "ext" and \
                    desc.get("mod") == "numpy" and \
                    attr in NP_SYNC_FUNCS:
                # jnp.* is traced, not a sync — only REAL numpy
                # materialises device buffers on the host
                effect("host_sync", f"np.{attr}", dd, node.lineno, locks)
            if loop > 0:
                ref = self._class_ref(f) if isinstance(f, ast.Name) \
                    else None
                leaf = (ref or "").rsplit(".", 1)[-1]
                if (ref and leaf[:1].isupper()) or attr in ALLOC_ATTRS:
                    effect("alloc", "per_item", dd or leaf, node.lineno,
                           locks, loop)

            # -- spawn / deferred refs ------------------------------------
            refs = []
            for kw in node.keywords:
                r = self._func_ref(kw.value, qual, local_defs)
                if r:
                    refs.append({"kw": kw.arg, "ref": r})
            for i, a in enumerate(node.args):
                r = self._func_ref(a, qual, local_defs)
                if r:
                    refs.append({"pos": i, "ref": r})
            if refs:
                desc["refs"] = refs
            if dd in ("threading.Thread", "Thread"):
                target = next((r["ref"] for r in refs
                               if r.get("kw") == "target"), None)
                nkw = next((kw.value for kw in node.keywords
                            if kw.arg == "name"), None)
                desc["spawn"] = {"target": target,
                                 "name": _name_prefix(nkw)}
            if dd.endswith("functools.partial") or dd == "partial":
                if node.args:
                    r = self._func_ref(node.args[0], qual, local_defs)
                    if r:
                        desc["partial"] = r
            rec["calls"].append(desc)

        def walk(node, locks, loop):
            if isinstance(node, ast.With):
                entered = list(locks)
                for item in node.items:
                    ln = lockname_of(item.context_expr)
                    if ln:
                        rec["acquires"].append(
                            {"lock": ln, "line": node.lineno,
                             "held": list(entered),
                             "text": text(node.lineno)})
                        entered.append(ln)
                for child in node.body:
                    walk(child, tuple(entered), loop)
                return
            if isinstance(node, ast.Call):
                call_desc(node, locks, loop)
                for child in ast.iter_child_nodes(node):
                    walk(child, locks, loop)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: body runs LATER, not under these locks —
                # extracted as its own function below
                nested_qual = f"{qual}.<locals>.{node.name}"
                self._extract_func(node, nested_qual, cls)
                return
            if isinstance(node, ast.Lambda):
                return
            if isinstance(node, (ast.For, ast.While, ast.ListComp,
                                 ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for child in ast.iter_child_nodes(node):
                    walk(child, locks, loop + 1)
                return
            if isinstance(node, ast.Raise):
                # an exception ctor is the loop's EXIT, not a per-item
                # allocation — drop the loop context for the alloc pass
                for child in ast.iter_child_nodes(node):
                    walk(child, locks, 0)
                return
            if is_jit and isinstance(node, (ast.If, ast.While)) and \
                    hasattr(node, "test"):
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Attribute) and \
                            sub.attr == "shape":
                        effect("jit_branch", "shape", "shape-dependent "
                               "branch", node.lineno, locks)
                        break
                    if isinstance(sub, ast.Name) and \
                            sub.id in params and \
                            sub.id not in static_params and \
                            isinstance(node.test, ast.Name):
                        effect("jit_branch", "tracer-bool",
                               f"branch on traced arg {sub.id!r}",
                               node.lineno, locks)
                        break
            if is_jit and isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                effect("blocking", "print", "print", node.lineno, locks)
            for child in ast.iter_child_nodes(node):
                walk(child, locks, loop)

        for st in fn.body:
            walk(st, (), 0)

        # `jax.jit(run)` wrapping a nested def marks it jit after the fact
        for c in rec["calls"]:
            pass  # (handled in linker via JIT_WRAP below)

    def _func_ref(self, expr: ast.expr, encl_qual: str,
                  local_defs: set) -> Optional[dict]:
        """A Name/Attribute argument that may be a function value."""
        if isinstance(expr, ast.Name):
            if expr.id in local_defs:
                return {"t": "qual",
                        "q": f"{encl_qual}.<locals>.{expr.id}"}
            imp = self.imports.get(expr.id)
            if imp and imp[0] == "sym":
                return {"t": "symbol", "mod": imp[1], "name": imp[2]}
            if imp is None:
                return {"t": "bare", "name": expr.id}
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls"):
            return {"t": "self", "attr": expr.attr}
        return None

    def summary(self) -> dict:
        return {"module": self.module, "relpath": self.relpath,
                "imports": self.imports, "classes": self.classes,
                "funcs": self.funcs, "suppress": self.suppress}


def extract_module(src: str, relpath: str) -> dict:
    return _ModuleExtract(src, relpath).summary()


# ---------------------------------------------------------------------------
# linking: summaries -> call graph
# ---------------------------------------------------------------------------

class Graph:
    def __init__(self, summaries: dict[str, dict]):
        self.summaries = summaries          # relpath -> module summary
        self.funcs: dict[str, dict] = {}    # qual -> func record
        self.classes: dict[str, dict] = {}  # "module.Cls" -> class info
        self.subclasses: dict[str, list] = {}
        self.method_index: dict[str, list] = {}
        self.module_of: dict[str, str] = {}
        self.edges: dict[str, list] = {}    # qual -> [target quals]
        self.redges: dict[str, list] = {}   # reverse (call + ref)
        self.ref_edges: dict[str, list] = {}
        self.roots: list[tuple[str, str, str]] = []  # (qual, plane, why)
        self.stats = {"files": 0, "defs": 0, "classes": 0,
                      "sites": 0, "candidates": 0, "resolved": 0,
                      "generic_skipped": 0}
        self._reach: dict[str, frozenset] = {}
        self._build_indexes()
        self._link()
        self._find_roots()

    # -- indexes -----------------------------------------------------------
    def _build_indexes(self) -> None:
        for rel, s in self.summaries.items():
            self.stats["files"] += 1
            mod = s["module"]
            for cname, cinfo in s["classes"].items():
                self.classes[f"{mod}.{cname}"] = cinfo
            for qual, f in s["funcs"].items():
                self.funcs[qual] = f
                self.module_of[qual] = mod
                if f["cls"] and "<locals>" not in qual:
                    self.method_index.setdefault(f["name"], []).append(qual)
        self.stats["defs"] = len(self.funcs)
        self.stats["classes"] = len(self.classes)
        for cq, ci in self.classes.items():
            for b in ci["bases"]:
                bq = self.resolve_class(b)
                if bq:
                    self.subclasses.setdefault(bq, []).append(cq)

    def resolve_class(self, ref: Optional[str]) -> Optional[str]:
        """Dotted ref -> canonical class qual, chasing one or two levels
        of package __init__ re-exports ('protocol.Block' ->
        'protocol.block.Block')."""
        if not ref:
            return None
        for _ in range(3):
            if ref in self.classes:
                return ref
            mod, _, name = ref.rpartition(".")
            s = self._summary_of_module(mod)
            if s is None:
                return None
            imp = s["imports"].get(name)
            if imp and imp[0] == "sym":
                ref = f"{imp[1]}.{imp[2]}"
            else:
                return None
        return ref if ref in self.classes else None

    def _summary_of_module(self, mod: str) -> Optional[dict]:
        for s in self.summaries.values():
            if s["module"] == mod:
                return s
        return None

    def find_method(self, clsqual: str, name: str,
                    seen=None) -> Optional[str]:
        if seen is None:
            seen = set()
        if clsqual in seen or clsqual not in self.classes:
            return None
        seen.add(clsqual)
        ci = self.classes[clsqual]
        if name in ci["methods"]:
            return f"{clsqual}.{name}"
        for b in ci["bases"]:
            bq = self.resolve_class(b)
            if bq:
                hit = self.find_method(bq, name, seen)
                if hit:
                    return hit
        return None

    def _overrides(self, clsqual: str, name: str) -> list:
        """Methods named `name` on transitive subclasses of clsqual."""
        out, todo, seen = [], [clsqual], set()
        while todo:
            c = todo.pop()
            if c in seen:
                continue
            seen.add(c)
            for sub in self.subclasses.get(c, ()):  # CHA dispatch
                if name in self.classes[sub]["methods"]:
                    out.append(f"{sub}.{name}")
                todo.append(sub)
        return out

    # -- resolution --------------------------------------------------------
    def _resolve_desc(self, desc: dict, encl: dict) -> tuple[list, bool]:
        """-> (target quals, counts_in_denominator)."""
        t = desc["t"]
        mod = encl["module"]
        if t == "qual":
            q = desc["q"]
            return ([q] if q in self.funcs else [], True)
        if t == "bare":
            n = desc["name"]
            q = f"{mod}.{n}"
            if q in self.funcs:
                return [q], True
            cq = self.resolve_class(f"{mod}.{n}")
            if cq:
                ctor = self.find_method(cq, "__init__")
                return ([ctor] if ctor else []), True
            return [], False  # builtin / unresolvable bare name
        if t == "symbol":
            ref = f"{desc['mod']}.{desc['name']}"
            q = self._resolve_symbol(ref)
            if q:
                return q, True
            # symbol imported from a repo module but not found: external
            # re-export or dynamic — count only if it LOOKS like ours
            return [], desc["mod"] in {s["module"]
                                       for s in self.summaries.values()}
        if t == "modfunc":
            q = self._resolve_symbol(f"{desc['mod']}.{desc['name']}")
            return (q or []), True
        if t in ("self", "super"):
            cls = encl["cls"] if t == "self" else desc.get("cls")
            if not cls:
                return [], False
            cq = f"{mod}.{cls}"
            if t == "super":
                ci = self.classes.get(cq)
                hit = None
                if ci:
                    for b in ci["bases"]:
                        bq = self.resolve_class(b)
                        if bq:
                            hit = self.find_method(bq, desc["attr"])
                            if hit:
                                break
                return ([hit] if hit else []), True
            hit = self.find_method(cq, desc["attr"])
            targets = [hit] if hit else []
            targets += self._overrides(cq, desc["attr"])
            return list(dict.fromkeys(targets)), True
        if t == "typed":
            cq = self.resolve_class(desc["cls"])
            if cq is None:
                # typed ref didn't resolve to a repo class (external)
                return [], False
            attr = desc["attr"]
            if attr == "__init__" or attr == cq.rsplit(".", 1)[-1]:
                hit = self.find_method(cq, "__init__")
                return ([hit] if hit else []), True
            hit = self.find_method(cq, attr)
            targets = ([hit] if hit else []) + self._overrides(cq, attr)
            return list(dict.fromkeys(targets)), True
        if t == "unknown":
            attr = desc["attr"]
            if attr in GENERIC_NAMES:
                self.stats["generic_skipped"] += 1
                return [], False
            cands = self.method_index.get(attr, [])
            if 1 <= len(cands) <= CHA_CAP:
                return list(cands), True
            return [], bool(cands)  # too many same-name: honest miss
        if t == "ext":
            return [], False
        return [], False

    def _resolve_symbol(self, ref: str) -> Optional[list]:
        """'module.sym' -> [func qual] (function, or class -> its ctor),
        chasing __init__ re-exports."""
        for _ in range(3):
            if ref in self.funcs:
                return [ref]
            if ref in self.classes:
                ctor = self.find_method(ref, "__init__")
                return [ctor] if ctor else []
            mod, _, name = ref.rpartition(".")
            s = self._summary_of_module(mod)
            if s is None:
                return None
            imp = s["imports"].get(name)
            if imp and imp[0] == "sym":
                ref = f"{imp[1]}.{imp[2]}"
            else:
                return None
        return None

    def _link(self) -> None:
        jit_wrapped: set[str] = set()
        for qual, f in self.funcs.items():
            targets: list[str] = []
            refs: list[str] = []
            for c in f["calls"]:
                self.stats["sites"] += 1
                tg, counts = self._resolve_desc(c, f)
                if counts:
                    self.stats["candidates"] += 1
                    if tg:
                        self.stats["resolved"] += 1
                c["targets"] = tg
                if "spawn" in c:
                    # thread target runs on ITS OWN plane, not as a call
                    pass
                else:
                    targets += tg
                for r in c.get("refs", []):
                    rq, _ = self._resolve_desc(
                        {**r["ref"], "line": c["line"]}, f)
                    r["targets"] = rq
                    refs += rq
                if "partial" in c:
                    pq, _ = self._resolve_desc(
                        {**c["partial"], "line": c["line"]}, f)
                    c["partial_targets"] = pq
                    refs += pq
                # `x = jax.jit(run)` / `return jax.jit(run)`
                dd = c.get("attr") or c.get("name") or ""
                if c["t"] == "ext" and c.get("mod") == "jax" and \
                        dd == "jit":
                    for r in c.get("refs", []):
                        jit_wrapped.update(r.get("targets", []))
            self.edges[qual] = list(dict.fromkeys(targets))
            self.ref_edges[qual] = list(dict.fromkeys(refs))
        for q in jit_wrapped:
            if q in self.funcs:
                self.funcs[q]["jit"] = True
        for src, ts in self.edges.items():
            for t in ts:
                self.redges.setdefault(t, []).append(src)
        for src, ts in self.ref_edges.items():
            for t in ts:
                self.redges.setdefault(t, []).append(src)

    # -- roots / planes ----------------------------------------------------
    def _classify_name(self, name: str) -> str:
        for prefix, role in planes.EXTRA_ROLE_PREFIXES:
            if name.startswith(prefix):
                return role
        return profiler.classify(name)

    def _find_roots(self) -> None:
        seen = set()

        def add(qual, plane, why):
            if qual in self.funcs and (qual, plane) not in seen:
                seen.add((qual, plane))
                self.roots.append((qual, plane, why))

        for qual, plane in planes.ROOT_OVERRIDES.items():
            add(qual, plane, "override")
        # columnar hot-path entry points: roots regardless of which
        # thread reaches them (hot-loop-alloc guard rail, ROADMAP-1)
        for qual, plane in planes.HOT_PATH_EXTRA_ROOTS.items():
            add(qual, plane, "columnar hot-path entry")
        worker_base = None
        for cq in self.classes:
            if cq.endswith("utils.worker.Worker") or cq == "utils.worker.Worker":
                worker_base = cq
        for qual, f in self.funcs.items():
            for c in f["calls"]:
                sp = c.get("spawn")
                if sp and sp.get("target"):
                    tq, _ = self._resolve_desc(
                        {**sp["target"], "line": c["line"]}, f)
                    for q in tq:
                        plane = self._classify_name(sp["name"] or "") \
                            if sp.get("name") else None
                        if plane is None or plane == "other":
                            plane = planes.ROOT_OVERRIDES.get(q, "other")
                        add(q, plane, f"Thread in {qual}")
                for r in c.get("refs", []):
                    plane = None
                    attr = c.get("attr") or c.get("name") or ""
                    if attr in planes.CALLBACK_PLANES:
                        plane = planes.CALLBACK_PLANES[attr]
                    ckey = (attr, r.get("kw"))
                    if ckey in planes.CTOR_CALLBACK_KWARGS:
                        plane = planes.CTOR_CALLBACK_KWARGS[ckey]
                    if plane:
                        for q in r.get("targets", []):
                            add(q, plane, f"callback via {attr} in {qual}")
        # Worker subclasses: the loop thread's body is execute_worker();
        # the plane comes from the literal name in super().__init__("...")
        if worker_base:
            for sub in self.subclasses.get(worker_base, []):
                ctor = self.find_method(sub, "__init__")
                name = None
                if ctor and ctor in self.funcs:
                    for c in self.funcs[ctor]["calls"]:
                        if c["t"] == "super" and c["attr"] == "__init__":
                            name = c.get("ctor_name")
                # fall back to scanning the ctor source line via calls'
                # recorded name literal (stored by extractor below)
                name = name or self.classes[sub].get("worker_name")
                plane = self._classify_name(name) if name else "other"
                ew = self.find_method(sub, "execute_worker")
                if ew:
                    add(ew, plane, f"Worker subclass {sub}")
        # deep subclasses of Worker subclasses inherit via _overrides
        # already (execute_worker override fan-out at the call site).

    # -- reachability ------------------------------------------------------
    def reach(self, qual: str) -> frozenset:
        """All functions transitively callable from qual (call edges)."""
        hit = self._reach.get(qual)
        if hit is not None:
            return hit
        seen = set()
        todo = [qual]
        while todo:
            q = todo.pop()
            for t in self.edges.get(q, ()):
                if t not in seen:
                    seen.add(t)
                    todo.append(t)
        fs = frozenset(seen)
        self._reach[qual] = fs
        return fs

    def chain(self, src: str, dst: str, maxlen: int = 10) -> list[str]:
        """Shortest call path src -> dst (BFS, for finding messages)."""
        if src == dst:
            return [src]
        parent = {src: None}
        todo = [src]
        while todo:
            nxt = []
            for q in todo:
                for t in self.edges.get(q, ()):
                    if t in parent:
                        continue
                    parent[t] = q
                    if t == dst:
                        out = [t]
                        while parent[out[-1]] is not None:
                            out.append(parent[out[-1]])
                        return list(reversed(out))[:maxlen]
                    nxt.append(t)
            todo = nxt
        return [src, "...", dst]

    def dump(self) -> dict:
        return {
            "stats": dict(self.stats,
                          resolution=self.resolution_rate()),
            "roots": [{"func": q, "plane": p, "why": w}
                      for q, p, w in self.roots],
            "functions": [
                {"qual": q, "path": f["path"], "line": f["line"],
                 "jit": f["jit"], "fp_armed": f["fp_armed"],
                 "effects": [{k: e[k] for k in
                              ("cat", "kind", "what", "line")}
                             for e in f["effects"]],
                 "acquires": [{"lock": a["lock"], "line": a["line"]}
                              for a in f["acquires"]]}
                for q, f in sorted(self.funcs.items())],
            "edges": [[s, t] for s, ts in sorted(self.edges.items())
                      for t in ts],
            "ref_edges": [[s, t]
                          for s, ts in sorted(self.ref_edges.items())
                          for t in ts],
        }

    def resolution_rate(self) -> float:
        c = self.stats["candidates"]
        return (self.stats["resolved"] / c) if c else 1.0


# ---------------------------------------------------------------------------
# findings + passes
# ---------------------------------------------------------------------------

class Finding(bcoslint.Violation):
    """Same key/fingerprint/baseline semantics as a bcoslint Violation;
    carries the interprocedural witness chain in the message."""


def _suppressed(summary: dict, line: int, rule: str) -> bool:
    for ln in (line, line - 1):
        rules = summary["suppress"].get(ln)
        if rules is not None:
            if rules == "all" or rule in [r.strip()
                                          for r in rules.split(",")]:
                return True
    return False


def _scope_of(qual: str, f: dict) -> str:
    mod = f["module"]
    return qual[len(mod) + 1:] if qual.startswith(mod + ".") else qual


def _fmt_chain(chain: list[str]) -> str:
    # trim module prefixes for readability; keep first and last full
    if len(chain) <= 1:
        return chain[0] if chain else ""
    tail = [q.rsplit(".", 1)[-1] if q != "..." else q
            for q in chain[1:-1]]
    return " -> ".join([chain[0]] + tail + [chain[-1]])


class Analyzer:
    def __init__(self, graph: Graph):
        self.g = graph
        self.findings: list[Finding] = []

    def _summary_for(self, f: dict) -> dict:
        return self.g.summaries[f["relpath"]] \
            if f["relpath"] in self.g.summaries else \
            next(s for s in self.g.summaries.values()
                 if s["module"] == f["module"])

    def _emit(self, rule: str, qual: str, line: int, text: str,
              message: str) -> None:
        f = self.g.funcs[qual]
        s = next(s for s in self.g.summaries.values()
                 if s["module"] == f["module"]
                 and qual in s["funcs"])
        if _suppressed(s, line, rule):
            return
        self.findings.append(Finding(
            rule=rule, path=f["path"], line=line,
            scope=_scope_of(qual, f), text=text, message=message))

    def run(self) -> list[Finding]:
        self.pass_plane_blocking()
        self.pass_lock_blocking()
        self.pass_lock_order()
        self.pass_fsync_coverage()
        self.pass_lane_host_sync()
        self.pass_jit()
        self.pass_hot_loop_alloc()
        return self.findings

    # -- pass: plane contracts --------------------------------------------
    def pass_plane_blocking(self) -> None:
        done = set()
        for root, plane, _why in self.g.roots:
            forbid = planes.PLANE_CONTRACTS.get(plane)
            if not forbid:
                continue
            for q in [root, *self.g.reach(root)]:
                f = self.g.funcs.get(q)
                if f is None:
                    continue
                for e in f["effects"]:
                    if e["cat"] != "blocking" or e["kind"] not in forbid:
                        continue
                    key = (plane, q, e["kind"])
                    if key in done:
                        continue
                    done.add(key)
                    chain = self.g.chain(root, q)
                    self._emit(
                        "plane-blocking", q, e["line"], e["text"],
                        f"{e['what']} ({e['kind']}) reachable from the "
                        f"'{plane}' plane (root {root}) — forbidden by "
                        f"the plane contract (analysis/planes.py); "
                        f"path: {_fmt_chain(chain)}")

    # -- pass: blocking under a hot lock, across call boundaries ----------
    def pass_lock_blocking(self) -> None:
        done = set()
        for qual, f in self.g.funcs.items():
            for c in f["calls"]:
                held = [L for L in c.get("locks", ())
                        if L in lockorder.HOT_LOCKS]
                if not held:
                    continue
                for t in c.get("targets", []):
                    for q in [t, *self.g.reach(t)]:
                        g = self.g.funcs.get(q)
                        if g is None or q == qual:
                            continue
                        for e in g["effects"]:
                            if e["cat"] != "blocking":
                                continue
                            for L in held:
                                allow = lockorder.HOT_LOCKS[L]
                                if e["kind"] in allow or \
                                        e["kind"] == "print":
                                    continue
                                key = (L, q, e["kind"])
                                if key in done:
                                    continue
                                done.add(key)
                                chain = self.g.chain(t, q)
                                self._emit(
                                    "lock-blocking-interproc", q,
                                    e["line"], e["text"],
                                    f"{e['what']} ({e['kind']}) runs "
                                    f"under hot lock {L} held in {qual} "
                                    f"(line {c['line']}); path: "
                                    f"{qual} -> {_fmt_chain(chain)}")

    # -- pass: interprocedural lock ordering -------------------------------
    def pass_lock_order(self) -> None:
        done = set()
        for qual, f in self.g.funcs.items():
            for c in f["calls"]:
                ranked = [L for L in c.get("locks", ())
                          if L in lockorder.RANK]
                if not ranked:
                    continue
                for t in c.get("targets", []):
                    for q in [t, *self.g.reach(t)]:
                        g = self.g.funcs.get(q)
                        if g is None or q == qual:
                            continue
                        for a in g["acquires"]:
                            M = a["lock"]
                            rb = lockorder.RANK.get(M)
                            if rb is None:
                                continue
                            for L in ranked:
                                ra = lockorder.RANK[L]
                                if M == L or ra < rb:
                                    continue
                                key = (L, M, q)
                                if key in done:
                                    continue
                                done.add(key)
                                chain = self.g.chain(t, q)
                                self._emit(
                                    "lock-order-interproc", q,
                                    a["line"], a["text"],
                                    f"acquires {M} (rank {rb}) while "
                                    f"{L} (rank {ra}) is held in {qual} "
                                    f"(line {c['line']}) — canonical "
                                    f"order inverted across calls; "
                                    f"path: {qual} -> "
                                    f"{_fmt_chain(chain)}")

    # -- pass: whole-program failpoint coverage of durability edges --------
    def pass_fsync_coverage(self) -> None:
        for qual, f in self.g.funcs.items():
            if not any(f["path"].startswith(p) for p in FSYNC_FP_SCOPE):
                continue
            sites = [e for e in f["effects"]
                     if e["cat"] == "blocking" and e["kind"] == "fsync"
                     and e["what"] != "note_blocking marker"]
            if not sites or f["fp_armed"]:
                continue
            # covered iff EVERY path from an entry point down to this
            # function crosses a failpoint-armed function
            bare = self._unarmed_entry_chain(qual)
            if bare is None:
                continue
            e = sites[0]
            self._emit(
                "fsync-path-unarmed", qual, e["line"], e["text"],
                f"{e['what']} (durability edge) with no failpoint "
                f"site on the call path from {bare[0]} "
                f"({_fmt_chain(bare)}) — the kill -9 matrix cannot "
                f"exercise this edge (utils/failpoints.py)")

    def _unarmed_entry_chain(self, qual: str) -> Optional[list]:
        """A caller chain entry->qual crossing NO fp-armed function, or
        None if every path is armed. DFS over reverse edges."""
        seen = set()
        stack = [(qual, [qual])]
        while stack:
            q, path = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            callers = self.g.redges.get(q, [])
            callers = [c for c in callers if c not in path]  # no cycles
            if not callers:
                return list(reversed(path))  # reached an entry, unarmed
            for c in callers:
                fc = self.g.funcs.get(c)
                if fc is None or fc["fp_armed"]:
                    continue  # this path is armed (or leaves the repo)
                stack.append((c, path + [c]))
        return None

    # -- pass: host syncs reachable from the lane dispatcher ---------------
    def pass_lane_host_sync(self) -> None:
        done = set()
        for root, plane, _why in self.g.roots:
            if plane != "lane":
                continue
            for q in [root, *self.g.reach(root)]:
                f = self.g.funcs.get(q)
                if f is None:
                    continue
                if any(f["path"].startswith(p)
                       for p in planes.LANE_SYNC_BOUNDARY):
                    continue
                for e in f["effects"]:
                    if e["cat"] != "host_sync":
                        continue
                    key = (q, e["line"])
                    if key in done:
                        continue
                    done.add(key)
                    chain = self.g.chain(root, q)
                    self._emit(
                        "lane-host-sync", q, e["line"], e["text"],
                        f"{e['what']} host<->device sync reachable from "
                        f"the crypto-lane dispatcher OUTSIDE the "
                        f"sanctioned demux boundary; path: "
                        f"{_fmt_chain(chain)}")

    # -- pass: jit purity --------------------------------------------------
    def pass_jit(self) -> None:
        for qual, f in self.g.funcs.items():
            if not f["jit"]:
                continue
            for e in f["effects"]:
                if e["cat"] == "blocking":
                    self._emit(
                        "jit-impure", qual, e["line"], e["text"],
                        f"{e['what']} ({e['kind']}) inside a jit-traced "
                        f"function — side effects run ONCE at trace "
                        f"time, then never again")
                elif e["cat"] == "host_sync":
                    self._emit(
                        "jit-impure", qual, e["line"], e["text"],
                        f"{e['what']} inside a jit-traced function — "
                        f"forces a host sync / breaks the trace")
                elif e["cat"] == "jit_branch":
                    self._emit(
                        "jit-shape-branch", qual, e["line"], e["text"],
                        f"{e['what']} inside a jit body — one compile "
                        f"per encountered shape; pad through the bucket "
                        f"discipline instead")

    # -- pass: per-item allocation on the hot path -------------------------
    def pass_hot_loop_alloc(self) -> None:
        done = set()
        for root, plane, _why in self.g.roots:
            if plane not in planes.HOT_PATH_PLANES:
                continue
            for q in [root, *self.g.reach(root)]:
                f = self.g.funcs.get(q)
                if f is None:
                    continue
                if not any(f["path"].startswith(p)
                           for p in planes.HOT_ALLOC_SCOPE):
                    continue
                for e in f["effects"]:
                    if e["cat"] != "alloc":
                        continue
                    key = (q, e["line"])
                    if key in done:
                        continue
                    done.add(key)
                    chain = self.g.chain(root, q)
                    self._emit(
                        "hot-loop-alloc", q, e["line"], e["text"],
                        f"per-item object construction ({e['what']}) in "
                        f"a loop on the '{plane}' hot path — the "
                        f"columnar contract (ROADMAP-1) wants batch "
                        f"arrays, not N Python objects; path: "
                        f"{_fmt_chain(chain)}")


RULES = ("plane-blocking", "lock-blocking-interproc",
         "lock-order-interproc", "fsync-path-unarmed", "lane-host-sync",
         "jit-impure", "jit-shape-branch", "hot-loop-alloc")


# ---------------------------------------------------------------------------
# worker-name sidecar: the extractor stores the literal passed to
# super().__init__ on the class, so the linker can classify Worker planes
# ---------------------------------------------------------------------------

_orig_extract = _ModuleExtract._extract_func


def _extract_func_with_worker_name(self, fn, qual, cls):
    _orig_extract(self, fn, qual, cls)
    if fn.name != "__init__" or cls is None:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "__init__" and \
                isinstance(node.func.value, ast.Call) and \
                (_dotted(node.func.value.func) or "") == "super":
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                self.classes[cls]["worker_name"] = node.args[0].value


_ModuleExtract._extract_func = _extract_func_with_worker_name


# ---------------------------------------------------------------------------
# driver: files -> summaries (cached) -> graph -> findings
# ---------------------------------------------------------------------------

def _sha(src: bytes) -> str:
    return hashlib.sha1(src).hexdigest()


def load_summaries(paths: list[str], cache_file: Optional[str] = None
                   ) -> tuple[dict, dict]:
    """-> ({relpath: summary}, cache_stats)."""
    cache = {"version": SUMMARY_VERSION, "files": {}}
    if cache_file and os.path.exists(cache_file):
        try:
            loaded = json.load(open(cache_file, encoding="utf-8"))
            if loaded.get("version") == SUMMARY_VERSION:
                cache = loaded
        except (OSError, ValueError):
            pass
    summaries: dict[str, dict] = {}
    hits = misses = 0
    new_cache = {"version": SUMMARY_VERSION, "files": {}}
    for path in bcoslint.iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), REPO).replace(
            os.sep, "/")
        try:
            raw = open(path, "rb").read()
        except OSError:
            continue
        sha = _sha(raw)
        ent = cache["files"].get(rel)
        if ent and ent.get("sha") == sha:
            summary = ent["summary"]
            # JSON round-trip turns int keys into strings
            summary["suppress"] = {int(k): v for k, v in
                                   summary["suppress"].items()}
            hits += 1
        else:
            try:
                summary = extract_module(raw.decode("utf-8"), rel)
            except (SyntaxError, UnicodeDecodeError) as exc:
                print(f"bcosflow: cannot parse {rel}: {exc}",
                      file=sys.stderr)
                continue
            misses += 1
        summaries[rel] = summary
        new_cache["files"][rel] = {"sha": sha, "summary": summary}
    if cache_file:
        try:
            with open(cache_file, "w", encoding="utf-8") as f:
                json.dump(new_cache, f)
        except OSError:
            pass
    return summaries, {"cache_hits": hits, "cache_misses": misses}


def analyze_sources(sources: dict[str, str]) -> tuple[list, Graph]:
    """Fixture entry point: {relpath: src} -> (findings, graph)."""
    summaries = {rel: extract_module(src, rel)
                 for rel, src in sources.items()}
    graph = Graph(summaries)
    return Analyzer(graph).run(), graph


def git_changed_files() -> Optional[set]:
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"], cwd=REPO, timeout=20,
            capture_output=True, text=True)
        if out.returncode != 0:
            return None
        changed = set()
        for ln in out.stdout.splitlines():
            p = ln[3:].split(" -> ")[-1].strip().strip('"')
            if p.endswith(".py"):
                changed.add(p)
        head = subprocess.run(
            ["git", "diff", "--name-only", "HEAD~1", "HEAD"], cwd=REPO,
            timeout=20, capture_output=True, text=True)
        if head.returncode == 0:
            for p in head.stdout.splitlines():
                if p.endswith(".py"):
                    changed.add(p.strip())
        return changed
    except (OSError, subprocess.SubprocessError):
        return None


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="findings as a JSON array on stdout")
    ap.add_argument("--graph", metavar="FILE",
                    help="dump the resolved call graph as JSON "
                    "('-' for stdout)")
    ap.add_argument("--stats", action="store_true",
                    help="print resolution/timing stats and exit 0")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for git-changed files "
                    "(cached module summaries make this fast)")
    ap.add_argument("--cache-file", default=DEFAULT_CACHE)
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(r)
        return 0

    t0 = time.monotonic()
    paths = args.paths or [os.path.join(REPO, PKG)]
    cache_file = None if args.no_cache else args.cache_file
    summaries, cstats = load_summaries(paths, cache_file)
    graph = Graph(summaries)
    findings = Analyzer(graph).run()
    elapsed = time.monotonic() - t0

    if args.graph:
        payload = json.dumps(graph.dump(), indent=1)
        if args.graph == "-":
            print(payload)
        else:
            with open(args.graph, "w", encoding="utf-8") as f:
                f.write(payload)
            print(f"bcosflow: graph -> {args.graph}")

    if args.update_baseline:
        old = bcoslint.load_baseline(args.baseline)
        bcoslint.write_baseline(args.baseline, findings, old)
        print(f"bcosflow: baseline rewritten with "
              f"{len({v.key for v in findings})} entr(y/ies) -> "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    scope = None
    if args.changed_only:
        scope = git_changed_files()
        if scope is not None:
            findings = [v for v in findings if v.path in scope]

    baseline = {} if args.no_baseline else \
        bcoslint.load_baseline(args.baseline)
    fresh = [v for v in findings if v.key not in baseline]
    stale = set(baseline) - {v.key for v in findings}
    if scope is not None:  # only judge staleness inside the scope
        stale = {k for k in stale if k[1] in scope}

    if args.json:
        print(json.dumps([{
            "rule": v.rule, "path": v.path, "line": v.line,
            "scope": v.scope, "message": v.message,
            "baselined": v.key in baseline} for v in findings], indent=1))
    else:
        for v in fresh:
            print(v.render())
        if stale and not args.changed_only:
            print(f"bcosflow: {len(stale)} stale baseline entr(y/ies) — "
                  "run --update-baseline to prune:", file=sys.stderr)
            for key in sorted(stale):
                print(f"    {key[0]}|{key[1]}|{key[2]}", file=sys.stderr)

    s = graph.stats
    print(f"bcosflow: {s['files']} files, {s['defs']} defs, "
          f"{s['resolved']}/{s['candidates']} intra-repo call edges "
          f"resolved ({100 * graph.resolution_rate():.1f}%), "
          f"{len(graph.roots)} plane roots, "
          f"{len(fresh)} new finding(s), "
          f"{len(findings) - len(fresh)} grandfathered, "
          f"{len(stale)} stale, "
          f"cache {cstats['cache_hits']}h/{cstats['cache_misses']}m, "
          f"{elapsed:.1f}s",
          file=sys.stderr if args.json or args.graph == "-" else
          sys.stdout)
    if args.stats:
        return 0
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
