// bcoskv — embedded LSM-style KV storage engine with WAL + 2PC.
//
// Fills the native-storage slot of the framework (the reference links
// RocksDB behind bcos-storage/bcos-storage/RocksDBStorage.h:64-68 and TiKV
// behind TiKVStorage.h:50-105; both implement the TransactionalStorage 2PC
// contract of bcos-framework/storage/StorageInterface.h:126-141).  This is
// an independent, purpose-built engine — not a RocksDB wrapper — sized for
// a consortium-chain node: block-batched writes, prefix scans for table
// iteration, crash-safe commit via a checksummed write-ahead log.
//
// Design:
//   * keys are opaque byte strings (the Python layer composes
//     "table\0key"); values opaque bytes; deletes are tombstones.
//   * memtable: std::map (ordered -> cheap prefix scans).
//   * WAL: [crc32][u64 len][payload] records, fsync'd per commit; replayed
//     over the SSTs at open; torn tails dropped.
//   * SST: immutable sorted file, [magic][count] + (klen,key,del,vlen,val)*;
//     an in-memory offset index is rebuilt at open (files are block-scale,
//     rebuilding is one sequential read).
//   * flush: memtable > threshold -> new SST, WAL truncated.  compaction:
//     too many SSTs -> full merge (newest wins, tombstones dropped in the
//     oldest level).
//   * 2PC: prepare(block) stages a changeset in memory; commit(block)
//     appends ONE atomic WAL record then applies to the memtable;
//     rollback discards.  Recovery therefore never sees half a block.
//
// C ABI at the bottom; bound from Python via ctypes (storage/native.py).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace bcoskv {

// ---------------------------------------------------------------------------
// crc32 (public-domain polynomial table, reflected 0xEDB88320)
// ---------------------------------------------------------------------------

static uint32_t crc_table[256];
static struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

static uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// little-endian IO helpers
// ---------------------------------------------------------------------------

static void put_u32(std::string& out, uint32_t v) {
  out.append(reinterpret_cast<const char*>(&v), 4);
}
static void put_u64(std::string& out, uint64_t v) {
  out.append(reinterpret_cast<const char*>(&v), 8);
}
static uint32_t get_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
static uint64_t get_u64(const uint8_t* p) { uint64_t v; memcpy(&v, p, 8); return v; }

struct Value {
  bool deleted;
  std::string data;
};

using MemTable = std::map<std::string, Value>;

// ---------------------------------------------------------------------------
// SSTable — immutable sorted run on disk
// ---------------------------------------------------------------------------

static constexpr uint32_t kSstMagic = 0x4B565353u;  // "SSVK"

class SSTable {
 public:
  explicit SSTable(std::string path) : path_(std::move(path)) {}

  bool load_index() {
    FILE* f = fopen(path_.c_str(), "rb");
    if (!f) return false;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    buf_.resize(static_cast<size_t>(sz));
    if (sz > 0 && fread(buf_.data(), 1, static_cast<size_t>(sz), f) !=
                      static_cast<size_t>(sz)) {
      fclose(f);
      return false;
    }
    fclose(f);
    if (buf_.size() < 8 || get_u32(buf_.data()) != kSstMagic) return false;
    uint32_t count = get_u32(buf_.data() + 4);
    size_t off = 8;
    index_.reserve(count);
    for (uint32_t i = 0; i < count; i++) {
      if (off + 4 > buf_.size()) return false;
      uint32_t klen = get_u32(buf_.data() + off);
      size_t koff = off + 4;
      if (koff + klen + 5 > buf_.size()) return false;
      uint32_t vlen = get_u32(buf_.data() + koff + klen + 1);
      if (koff + klen + 5 + vlen > buf_.size()) return false;
      index_.push_back(off);
      off = koff + klen + 5 + vlen;
    }
    return true;
  }

  size_t size() const { return index_.size(); }

  std::string_view key_at(size_t i) const {
    size_t off = index_[i];
    uint32_t klen = get_u32(buf_.data() + off);
    return {reinterpret_cast<const char*>(buf_.data() + off + 4), klen};
  }

  // (deleted, value)
  std::pair<bool, std::string_view> value_at(size_t i) const {
    size_t off = index_[i];
    uint32_t klen = get_u32(buf_.data() + off);
    size_t p = off + 4 + klen;
    bool del = buf_[p] != 0;
    uint32_t vlen = get_u32(buf_.data() + p + 1);
    return {del, {reinterpret_cast<const char*>(buf_.data() + p + 5), vlen}};
  }

  // smallest index with key >= target
  size_t lower_bound(std::string_view target) const {
    size_t lo = 0, hi = index_.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (key_at(mid) < target) lo = mid + 1; else hi = mid;
    }
    return lo;
  }

  std::optional<Value> get(std::string_view key) const {
    size_t i = lower_bound(key);
    if (i < index_.size() && key_at(i) == key) {
      auto [del, v] = value_at(i);
      return Value{del, std::string(v)};
    }
    return std::nullopt;
  }

  const std::string& path() const { return path_; }

 private:
  std::string path_;
  std::vector<uint8_t> buf_;
  std::vector<size_t> index_;
};

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

class Engine {
 public:
  Engine(std::string dir, size_t flush_bytes, size_t max_ssts)
      : dir_(std::move(dir)), flush_bytes_(flush_bytes), max_ssts_(max_ssts) {}

  bool open() {
    std::lock_guard<std::mutex> g(mu_);
    ::mkdir(dir_.c_str(), 0755);
    if (!load_manifest()) return false;
    if (!replay_wal()) return false;
    wal_ = ::open(wal_path().c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    return wal_ >= 0;
  }

  void close() {
    std::lock_guard<std::mutex> g(mu_);
    if (wal_ >= 0) ::close(wal_);
    wal_ = -1;
  }

  bool get(std::string_view key, std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = mem_.find(std::string(key));
    if (it != mem_.end()) {
      if (it->second.deleted) return false;
      *out = it->second.data;
      return true;
    }
    for (auto r = ssts_.rbegin(); r != ssts_.rend(); ++r) {
      auto v = (*r)->get(key);
      if (v) {
        if (v->deleted) return false;
        *out = std::move(v->data);
        return true;
      }
    }
    return false;
  }

  void put(std::string_view key, std::string_view val, bool del) {
    std::lock_guard<std::mutex> g(mu_);
    std::string payload = encode_changeset(
        0, {{std::string(key), Value{del, std::string(val)}}});
    append_wal(payload);
    apply(std::string(key), Value{del, std::string(val)});
    maybe_flush();
  }

  // prefix scan over the merged view; collects (key, value) pairs
  void scan(std::string_view prefix,
            std::vector<std::pair<std::string, std::string>>* out) {
    std::lock_guard<std::mutex> g(mu_);
    // merge: per-source cursor, smallest key wins; newer sources shadow
    struct Cur { size_t src; size_t i; };  // src: 0..ssts-1 old..new, mem = N
    std::map<std::string, std::pair<size_t, Value>> best;  // key -> (rank, v)
    size_t nsst = ssts_.size();
    for (size_t s = 0; s < nsst; s++) {
      auto& t = *ssts_[s];
      for (size_t i = t.lower_bound(prefix); i < t.size(); i++) {
        auto k = t.key_at(i);
        if (k.substr(0, prefix.size()) != prefix) break;
        auto [del, v] = t.value_at(i);
        auto& slot = best[std::string(k)];
        if (slot.first <= s + 1) slot = {s + 1, Value{del, std::string(v)}};
      }
    }
    for (auto it = mem_.lower_bound(std::string(prefix)); it != mem_.end();
         ++it) {
      if (it->first.compare(0, prefix.size(), prefix) != 0) break;
      best[it->first] = {nsst + 1, it->second};
    }
    for (auto& [k, rv] : best)
      if (!rv.second.deleted) out->emplace_back(k, rv.second.data);
  }

  // -- 2PC ------------------------------------------------------------------
  void prepare(uint64_t block, MemTable changes) {
    std::lock_guard<std::mutex> g(mu_);
    prepared_[block] = std::move(changes);
  }

  bool commit(uint64_t block) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = prepared_.find(block);
    if (it == prepared_.end()) return false;
    append_wal(encode_changeset(block, it->second));
    for (auto& [k, v] : it->second) apply(k, v);
    prepared_.erase(it);
    maybe_flush();
    return true;
  }

  void rollback(uint64_t block) {
    std::lock_guard<std::mutex> g(mu_);
    prepared_.erase(block);
  }

  bool flush() {
    std::lock_guard<std::mutex> g(mu_);
    return flush_locked();
  }

 private:
  std::string wal_path() const { return dir_ + "/wal.log"; }
  std::string manifest_path() const { return dir_ + "/MANIFEST"; }
  std::string sst_path(uint64_t seq) const {
    char buf[32];
    snprintf(buf, sizeof buf, "/%06llu.sst", (unsigned long long)seq);
    return dir_ + buf;
  }

  void apply(std::string key, Value v) {
    mem_bytes_ += key.size() + v.data.size() + 16;
    mem_[std::move(key)] = std::move(v);
  }

  static std::string encode_changeset(uint64_t block, const MemTable& cs) {
    std::string p;
    put_u64(p, block);
    put_u32(p, static_cast<uint32_t>(cs.size()));
    for (auto& [k, v] : cs) {
      p.push_back(v.deleted ? 1 : 0);
      put_u32(p, static_cast<uint32_t>(k.size()));
      p += k;
      put_u32(p, static_cast<uint32_t>(v.data.size()));
      p += v.data;
    }
    return p;
  }

  void append_wal(const std::string& payload) {
    std::string rec;
    put_u32(rec, crc32(reinterpret_cast<const uint8_t*>(payload.data()),
                       payload.size()));
    put_u64(rec, payload.size());
    rec += payload;
    ssize_t n = ::write(wal_, rec.data(), rec.size());
    (void)n;
    ::fsync(wal_);
  }

  bool replay_wal() {
    FILE* f = fopen(wal_path().c_str(), "rb");
    if (!f) return true;  // no WAL yet
    std::vector<uint8_t> raw;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    raw.resize(static_cast<size_t>(sz));
    if (sz > 0 && fread(raw.data(), 1, raw.size(), f) != raw.size()) {
      fclose(f);
      return false;
    }
    fclose(f);
    size_t off = 0;
    while (off + 12 <= raw.size()) {
      uint32_t crc = get_u32(raw.data() + off);
      uint64_t len = get_u64(raw.data() + off + 4);
      if (off + 12 + len > raw.size()) break;  // torn tail
      const uint8_t* p = raw.data() + off + 12;
      if (crc32(p, len) != crc) break;
      // payload: u64 block, u32 n, then entries
      if (len >= 12) {
        uint32_t n = get_u32(p + 8);
        size_t q = 12;
        for (uint32_t i = 0; i < n && q < len; i++) {
          bool del = p[q] != 0;
          q += 1;
          uint32_t klen = get_u32(p + q);
          q += 4;
          std::string key(reinterpret_cast<const char*>(p + q), klen);
          q += klen;
          uint32_t vlen = get_u32(p + q);
          q += 4;
          std::string val(reinterpret_cast<const char*>(p + q), vlen);
          q += vlen;
          apply(std::move(key), Value{del, std::move(val)});
        }
      }
      off += 12 + len;
    }
    return true;
  }

  bool load_manifest() {
    FILE* f = fopen(manifest_path().c_str(), "rb");
    if (!f) return true;
    char line[64];
    while (fgets(line, sizeof line, f)) {
      uint64_t seq = strtoull(line, nullptr, 10);
      auto sst = std::make_unique<SSTable>(sst_path(seq));
      if (!sst->load_index()) {
        fclose(f);
        return false;
      }
      ssts_.push_back(std::move(sst));
      next_seq_ = std::max(next_seq_, seq + 1);
    }
    fclose(f);
    return true;
  }

  bool write_manifest(const std::vector<uint64_t>& seqs) {
    std::string tmp = manifest_path() + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    for (uint64_t s : seqs) fprintf(f, "%llu\n", (unsigned long long)s);
    fflush(f);
    ::fsync(fileno(f));
    fclose(f);
    return ::rename(tmp.c_str(), manifest_path().c_str()) == 0;
  }

  void maybe_flush() {
    if (mem_bytes_ >= flush_bytes_) flush_locked();
  }

  bool flush_locked() {
    if (mem_.empty()) return true;
    uint64_t seq = next_seq_++;
    if (!write_sst(sst_path(seq), mem_)) return false;
    auto sst = std::make_unique<SSTable>(sst_path(seq));
    if (!sst->load_index()) return false;
    ssts_.push_back(std::move(sst));
    std::vector<uint64_t> seqs;
    for (auto& s : ssts_) {
      uint64_t v = strtoull(s->path().c_str() + dir_.size() + 1, nullptr, 10);
      seqs.push_back(v);
    }
    if (!write_manifest(seqs)) return false;
    mem_.clear();
    mem_bytes_ = 0;
    // truncate WAL: its contents are now durable in the SST
    if (wal_ >= 0) ::close(wal_);
    wal_ = ::open(wal_path().c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (ssts_.size() > max_ssts_) compact();
    return true;
  }

  static bool write_sst(const std::string& path, const MemTable& rows) {
    std::string out;
    put_u32(out, kSstMagic);
    put_u32(out, static_cast<uint32_t>(rows.size()));
    for (auto& [k, v] : rows) {
      put_u32(out, static_cast<uint32_t>(k.size()));
      out += k;
      out.push_back(v.deleted ? 1 : 0);
      put_u32(out, static_cast<uint32_t>(v.data.size()));
      out += v.data;
    }
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    if (!out.empty() && fwrite(out.data(), 1, out.size(), f) != out.size()) {
      fclose(f);
      return false;
    }
    fflush(f);
    ::fsync(fileno(f));
    fclose(f);
    return ::rename(tmp.c_str(), path.c_str()) == 0;
  }

  void compact() {
    // full merge, newest wins; tombstones dropped (single-level result)
    MemTable merged;
    for (auto& sst : ssts_)  // oldest -> newest: later overwrite earlier
      for (size_t i = 0; i < sst->size(); i++) {
        auto [del, v] = sst->value_at(i);
        merged[std::string(sst->key_at(i))] = Value{del, std::string(v)};
      }
    for (auto it = merged.begin(); it != merged.end();)
      it = it->second.deleted ? merged.erase(it) : std::next(it);
    uint64_t seq = next_seq_++;
    if (!write_sst(sst_path(seq), merged)) return;
    auto sst = std::make_unique<SSTable>(sst_path(seq));
    if (!sst->load_index()) return;
    std::vector<std::string> old_paths;
    for (auto& s : ssts_) old_paths.push_back(s->path());
    ssts_.clear();
    ssts_.push_back(std::move(sst));
    write_manifest({seq});
    for (auto& p : old_paths) ::unlink(p.c_str());
  }

  std::string dir_;
  size_t flush_bytes_;
  size_t max_ssts_;
  std::mutex mu_;
  MemTable mem_;
  size_t mem_bytes_ = 0;
  std::vector<std::unique_ptr<SSTable>> ssts_;
  std::map<uint64_t, MemTable> prepared_;
  uint64_t next_seq_ = 1;
  int wal_ = -1;
};

}  // namespace bcoskv

// ---------------------------------------------------------------------------
// C ABI (ctypes-friendly)
// ---------------------------------------------------------------------------

extern "C" {

#ifndef FBTPU_SRC_HASH
#define FBTPU_SRC_HASH "unstamped"
#endif
// sha256 of the source this binary was built from (see native/Makefile);
// Python loaders compare against the checked-in .cpp and refuse a
// drifted binary so stale consensus-critical semantics fail loudly
const char* bcoskv_src_hash(void) { return FBTPU_SRC_HASH; }

void* bcoskv_open(const char* dir, uint64_t flush_bytes, uint64_t max_ssts) {
  auto* e = new bcoskv::Engine(dir, flush_bytes ? flush_bytes : (8u << 20),
                               max_ssts ? max_ssts : 8);
  if (!e->open()) {
    delete e;
    return nullptr;
  }
  return e;
}

void bcoskv_close(void* h) {
  auto* e = static_cast<bcoskv::Engine*>(h);
  e->close();
  delete e;
}

// returns 1 if found; *out/*out_len owned by engine until bcoskv_free
int bcoskv_get(void* h, const uint8_t* key, uint64_t klen, uint8_t** out,
               uint64_t* out_len) {
  auto* e = static_cast<bcoskv::Engine*>(h);
  std::string v;
  if (!e->get({reinterpret_cast<const char*>(key), klen}, &v)) return 0;
  auto* buf = static_cast<uint8_t*>(malloc(v.size()));
  memcpy(buf, v.data(), v.size());
  *out = buf;
  *out_len = v.size();
  return 1;
}

void bcoskv_put(void* h, const uint8_t* key, uint64_t klen, const uint8_t* val,
                uint64_t vlen) {
  static_cast<bcoskv::Engine*>(h)->put(
      {reinterpret_cast<const char*>(key), klen},
      {reinterpret_cast<const char*>(val), vlen}, false);
}

void bcoskv_del(void* h, const uint8_t* key, uint64_t klen) {
  static_cast<bcoskv::Engine*>(h)->put(
      {reinterpret_cast<const char*>(key), klen}, {}, true);
}

// scan: packed result buffer u32 count, then (u32 klen, key, u32 vlen, val)*
int bcoskv_scan(void* h, const uint8_t* prefix, uint64_t plen, uint8_t** out,
                uint64_t* out_len) {
  auto* e = static_cast<bcoskv::Engine*>(h);
  std::vector<std::pair<std::string, std::string>> rows;
  e->scan({reinterpret_cast<const char*>(prefix), plen}, &rows);
  std::string packed;
  bcoskv::put_u32(packed, static_cast<uint32_t>(rows.size()));
  for (auto& [k, v] : rows) {
    bcoskv::put_u32(packed, static_cast<uint32_t>(k.size()));
    packed += k;
    bcoskv::put_u32(packed, static_cast<uint32_t>(v.size()));
    packed += v;
  }
  auto* buf = static_cast<uint8_t*>(malloc(packed.size()));
  memcpy(buf, packed.data(), packed.size());
  *out = buf;
  *out_len = packed.size();
  return 1;
}

void bcoskv_free(uint8_t* p) { free(p); }

// changeset payload: u32 n, then (u8 del, u32 klen, key, u32 vlen, val)*
int bcoskv_prepare(void* h, uint64_t block, const uint8_t* payload,
                   uint64_t len) {
  bcoskv::MemTable cs;
  if (len < 4) return 0;
  uint32_t n;
  memcpy(&n, payload, 4);
  size_t q = 4;
  for (uint32_t i = 0; i < n; i++) {
    if (q + 5 > len) return 0;
    bool del = payload[q] != 0;
    q += 1;
    uint32_t klen;
    memcpy(&klen, payload + q, 4);
    q += 4;
    if (q + klen + 4 > len) return 0;
    std::string key(reinterpret_cast<const char*>(payload + q), klen);
    q += klen;
    uint32_t vlen;
    memcpy(&vlen, payload + q, 4);
    q += 4;
    if (q + vlen > len) return 0;
    std::string val(reinterpret_cast<const char*>(payload + q), vlen);
    q += vlen;
    cs[std::move(key)] = bcoskv::Value{del, std::move(val)};
  }
  static_cast<bcoskv::Engine*>(h)->prepare(block, std::move(cs));
  return 1;
}

int bcoskv_commit(void* h, uint64_t block) {
  return static_cast<bcoskv::Engine*>(h)->commit(block) ? 1 : 0;
}

void bcoskv_rollback(void* h, uint64_t block) {
  static_cast<bcoskv::Engine*>(h)->rollback(block);
}

int bcoskv_flush(void* h) {
  return static_cast<bcoskv::Engine*>(h)->flush() ? 1 : 0;
}

}  // extern "C"
