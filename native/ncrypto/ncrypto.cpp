// ncrypto — native host-path EC signature engine for fisco-bcos-tpu.
//
// Reference counterpart: the WeDPR FFI natives behind
// /root/reference/bcos-crypto/bcos-crypto/signature/secp256k1/
// Secp256k1Crypto.cpp:40,57,85 and signature/sm2/SM2Crypto.h — the
// reference's per-signature hot functions are native; this framework's
// DEVICE path batches them on TPU (ops/ec.py), and this library is the
// native floor for the HOST path (sub-threshold batches, no-accelerator
// deployments, ingest fallback).
//
// Determinism contract: results must match crypto/refimpl.py exactly —
// including its edge semantics (coordinates implicitly reduced mod p, the
// final verify comparison mod n, recover's x = r + (v>>1)*n overflow
// behavior). tests/test_nativeec.py holds the equivalence suite.
//
// Implementation (batch-first, same shape as the TPU kernels):
//   * 4x64-limb Montgomery (CIOS) field arithmetic for all four moduli.
//   * GLV endomorphism split for secp256k1 (the same mul-shift
//     decomposition ops/ec.py and crypto/refimpl.glv_split use): both
//     ladder scalars become ~129-bit signed halves, halving the doubles.
//   * wNAF ladders — static affine odd-multiple tables for G and phi(G)
//     (window 7, built once per curve), per-signature Jacobian tables for
//     the variable point (window 5) normalised to affine via ONE shared
//     Montgomery-trick inversion per batch chunk, so every ladder add is
//     a mixed (affine) add.
//   * batch inversion for the per-signature scalar inverses (s^-1 / r^-1
//     mod n) and for the final Jacobian->affine conversions: three muls
//     per element instead of a ~380-mul Fermat inversion each.

#include <cstdint>
#include <cstring>
#include <functional>  // std::ref — not transitively included by older libstdc++
#include <mutex>

namespace {

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};
};

inline bool is_zero(const U256& a) {
  return !(a.w[0] | a.w[1] | a.w[2] | a.w[3]);
}

inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

inline uint64_t add_cc(const U256& a, const U256& b, U256& r) {
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 s = (unsigned __int128)a.w[i] + b.w[i] + c;
    r.w[i] = (uint64_t)s;
    c = s >> 64;
  }
  return (uint64_t)c;
}

inline uint64_t sub_bb(const U256& a, const U256& b, U256& r) {
  unsigned __int128 br = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a.w[i] - b.w[i] - br;
    r.w[i] = (uint64_t)d;
    br = (d >> 64) ? 1 : 0;
  }
  return (uint64_t)br;
}

inline void shr1(U256& a) {
  a.w[0] = (a.w[0] >> 1) | (a.w[1] << 63);
  a.w[1] = (a.w[1] >> 1) | (a.w[2] << 63);
  a.w[2] = (a.w[2] >> 1) | (a.w[3] << 63);
  a.w[3] >>= 1;
}

U256 from_be(const uint8_t* b) {
  U256 r;
  for (int i = 0; i < 32; ++i)
    r.w[(31 - i) / 8] |= (uint64_t)b[i] << (((31 - i) % 8) * 8);
  return r;
}

void to_be(const U256& v, uint8_t* out) {
  for (int i = 0; i < 32; ++i)
    out[i] = (uint8_t)(v.w[(31 - i) / 8] >> (((31 - i) % 8) * 8));
}

inline bool bit(const U256& v, int i) { return (v.w[i / 64] >> (i % 64)) & 1; }

int bitlen(const U256& v) {
  for (int i = 3; i >= 0; --i)
    if (v.w[i]) return i * 64 + 64 - __builtin_clzll(v.w[i]);
  return 0;
}

// full 256x256 -> 512-bit product, little-endian 8 limbs (GLV mul-shift)
void mul_wide(const U256& a, const U256& b, uint64_t out[8]) {
  memset(out, 0, 64);
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      unsigned __int128 cur =
          (unsigned __int128)a.w[i] * b.w[j] + out[i + j] + carry;
      out[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    out[i + 4] = (uint64_t)carry;
  }
}

// ---------------------------------------------------------------------------
// Montgomery field
// ---------------------------------------------------------------------------

struct Mont {
  U256 mod;
  uint64_t n0inv = 0;  // -mod^-1 mod 2^64
  U256 rr;             // 2^512 mod mod (to_mont multiplier)
  U256 one_m;          // 2^256 mod mod (Montgomery 1)
  // pseudo-Mersenne fast path: mod == 2^256 - kfold (secp256k1 field).
  // When set, the "Montgomery domain" IS the plain domain (to_mont and
  // from_mont are the identity) and mul/sqr reduce by folding the high
  // 256 bits times kfold — ~21 mul64 with short carry chains instead of
  // CIOS's 32 on a serial chain.
  uint64_t kfold = 0;

  void init(const U256& m) {
    mod = m;
    uint64_t x = m.w[0];  // Newton: x := x*(2 - m*x), doubles precision
    for (int i = 0; i < 6; ++i) x *= 2 - m.w[0] * x;
    n0inv = ~x + 1;  // -(m^-1) mod 2^64
    // detect 2^256 - k shape (k < 2^64): limbs 1..3 all ones
    if (m.w[1] == ~0ull && m.w[2] == ~0ull && m.w[3] == ~0ull) {
      kfold = ~m.w[0] + 1;  // 2^64 - w0 == k
      one_m.w[0] = 1;
      return;
    }
    U256 v;
    v.w[0] = 1;
    for (int i = 0; i < 256; ++i) v = dbl_mod(v);
    one_m = v;
    for (int i = 0; i < 256; ++i) v = dbl_mod(v);
    rr = v;
  }

  // reduce a 512-bit product (little-endian t[8]) modulo 2^256 - kfold
  U256 fold_reduce(const uint64_t t[8]) const {
    uint64_t r[4];
    unsigned __int128 cur;
    uint64_t carry = 0;
    for (int i = 0; i < 4; ++i) {
      cur = (unsigned __int128)t[4 + i] * kfold + t[i] + carry;
      r[i] = (uint64_t)cur;
      carry = (uint64_t)(cur >> 64);
    }
    // carry < kfold + 1; fold once more
    cur = (unsigned __int128)carry * kfold + r[0];
    r[0] = (uint64_t)cur;
    uint64_t c = (uint64_t)(cur >> 64);
    for (int i = 1; c && i < 4; ++i) {
      cur = (unsigned __int128)r[i] + c;
      r[i] = (uint64_t)cur;
      c = (uint64_t)(cur >> 64);
    }
    U256 out;
    memcpy(out.w, r, 32);
    if (c) {  // wrapped past 2^256: add kfold (== subtract mod)
      U256 kk;
      kk.w[0] = kfold;
      add_cc(out, kk, out);  // cannot carry again: out < kfold after wrap
    }
    if (cmp(out, mod) >= 0) {
      U256 o;
      sub_bb(out, mod, o);
      return o;
    }
    return out;
  }

  U256 dbl_mod(const U256& a) const {
    U256 r;
    uint64_t c = add_cc(a, a, r);
    U256 t;
    if (c || cmp(r, mod) >= 0) {
      sub_bb(r, mod, t);
      return t;
    }
    return r;
  }

  U256 add(const U256& a, const U256& b) const {
    U256 r, t;
    uint64_t c = add_cc(a, b, r);
    if (c || cmp(r, mod) >= 0) {
      sub_bb(r, mod, t);
      return t;
    }
    return r;
  }

  U256 sub(const U256& a, const U256& b) const {
    U256 r, t;
    if (sub_bb(a, b, r)) {
      add_cc(r, mod, t);
      return t;
    }
    return r;
  }

  U256 neg(const U256& a) const {
    if (is_zero(a)) return a;
    U256 r;
    sub_bb(mod, a, r);
    return r;
  }

  // CIOS Montgomery multiplication (pseudo-Mersenne moduli take the
  // plain-domain folding path instead)
  U256 mul(const U256& a, const U256& b) const {
    if (kfold) {
      uint64_t t[8];
      mul_wide(a, b, t);
      return fold_reduce(t);
    }
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
      unsigned __int128 carry = 0;
      for (int j = 0; j < 4; ++j) {
        unsigned __int128 cur =
            (unsigned __int128)a.w[i] * b.w[j] + t[j] + carry;
        t[j] = (uint64_t)cur;
        carry = cur >> 64;
      }
      unsigned __int128 cur = (unsigned __int128)t[4] + carry;
      t[4] = (uint64_t)cur;
      t[5] = (uint64_t)(cur >> 64);

      uint64_t m = t[0] * n0inv;
      carry = 0;
      unsigned __int128 c0 = (unsigned __int128)m * mod.w[0] + t[0];
      carry = c0 >> 64;
      for (int j = 1; j < 4; ++j) {
        unsigned __int128 cur2 =
            (unsigned __int128)m * mod.w[j] + t[j] + carry;
        t[j - 1] = (uint64_t)cur2;
        carry = cur2 >> 64;
      }
      unsigned __int128 c4 = (unsigned __int128)t[4] + carry;
      t[3] = (uint64_t)c4;
      t[4] = t[5] + (uint64_t)(c4 >> 64);
      t[5] = 0;
    }
    U256 r;
    memcpy(r.w, t, 32);
    if (t[4] || cmp(r, mod) >= 0) {
      U256 o;
      sub_bb(r, mod, o);
      return o;
    }
    return r;
  }

  U256 to_mont(const U256& a) const { return kfold ? a : mul(a, rr); }
  U256 from_mont(const U256& a) const {
    if (kfold) return a;
    U256 one;
    one.w[0] = 1;
    return mul(a, one);
  }

  // dedicated squaring: symmetric off-diagonal products once, doubled
  U256 sqr(const U256& a) const {
    if (!kfold) return mul(a, a);
    uint64_t t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    // off-diagonal sum: sum_{i<j} a_i a_j 2^(64(i+j))
    for (int i = 0; i < 4; ++i) {
      uint64_t carry = 0;
      for (int j = i + 1; j < 4; ++j) {
        unsigned __int128 cur =
            (unsigned __int128)a.w[i] * a.w[j] + t[i + j] + carry;
        t[i + j] = (uint64_t)cur;
        carry = (uint64_t)(cur >> 64);
      }
      t[i + 4] += carry;  // slot i+4 >= i+j+1 is untouched so far: no carry
    }
    // double the off-diagonal sum
    uint64_t c = 0;
    for (int i = 0; i < 8; ++i) {
      uint64_t hi = t[i] >> 63;
      t[i] = (t[i] << 1) | c;
      c = hi;
    }
    // add the diagonal a_i^2 terms
    unsigned __int128 cur;
    uint64_t carry = 0;
    for (int i = 0; i < 4; ++i) {
      unsigned __int128 sq = (unsigned __int128)a.w[i] * a.w[i];
      cur = (unsigned __int128)t[2 * i] + (uint64_t)sq + carry;
      t[2 * i] = (uint64_t)cur;
      carry = (uint64_t)(cur >> 64);
      cur = (unsigned __int128)t[2 * i + 1] + (uint64_t)(sq >> 64) + carry;
      t[2 * i + 1] = (uint64_t)cur;
      carry = (uint64_t)(cur >> 64);
    }
    return fold_reduce(t);
  }

  // a^e (a Montgomery, e plain), square-and-multiply MSB-first
  U256 pow(const U256& a, const U256& e) const {
    U256 acc = one_m;
    int n = bitlen(e);
    for (int i = n - 1; i >= 0; --i) {
      acc = sqr(acc);
      if (bit(e, i)) acc = mul(acc, a);
    }
    return acc;
  }

  // fixed-window (w=4) exponentiation: ~256 sqr + ~64+14 mul for a
  // 256-bit exponent vs square-and-multiply's ~256+hamming(e) — the sqrt
  // exponents (p+1)/4 are almost all ones, so this saves ~35% of the
  // recover sqrt cost
  U256 pow_win4(const U256& a, const U256& e) const {
    U256 tbl[16];
    tbl[0] = one_m;
    tbl[1] = a;
    for (int i = 2; i < 16; ++i) tbl[i] = mul(tbl[i - 1], a);
    int n = bitlen(e);
    if (n == 0) return one_m;
    int top = ((n + 3) / 4) * 4;
    U256 acc = one_m;
    for (int d = top - 4; d >= 0; d -= 4) {
      acc = sqr(sqr(sqr(sqr(acc))));
      unsigned dig = (unsigned)((e.w[d / 64] >> (d % 64)) & 0xF);
      if (dig) acc = mul(acc, tbl[dig]);
    }
    return acc;
  }

  U256 inv(const U256& a) const {  // Fermat (mod prime)
    U256 e = mod;
    U256 two;
    two.w[0] = 2;
    sub_bb(e, two, e);
    return pow_win4(a, e);
  }

  // plain value (possibly >= mod, < 2^256) -> canonical plain
  U256 reduce(const U256& a) const {
    if (cmp(a, mod) >= 0) {
      U256 r;
      sub_bb(a, mod, r);
      if (cmp(r, mod) >= 0) {  // inputs < 2^256 < 2*mod for our moduli,
        U256 r2;               // but stay safe
        sub_bb(r, mod, r2);
        return r2;
      }
      return r;
    }
    return a;
  }

  // plain a*b mod m via Montgomery round-trip (cold path: GLV split)
  U256 mulmod(const U256& a, const U256& b) const {
    return from_mont(mul(mul(a, rr), mul(b, rr)));
  }
};

// Montgomery-trick batch inversion: in/out Montgomery domain. Zero entries
// are passed through as zero (callers treat them as invalid lanes).
void batch_inv(const Mont& f, U256* vals, int n) {
  if (n <= 0) return;
  // prefix products over the non-zero entries
  U256* pref = new U256[n];
  U256 acc = f.one_m;
  for (int i = 0; i < n; ++i) {
    pref[i] = acc;
    if (!is_zero(vals[i])) acc = f.mul(acc, vals[i]);
  }
  U256 inv = f.inv(acc);
  for (int i = n - 1; i >= 0; --i) {
    if (is_zero(vals[i])) continue;
    U256 vi = f.mul(inv, pref[i]);
    inv = f.mul(inv, vals[i]);
    vals[i] = vi;
  }
  delete[] pref;
}

// ---------------------------------------------------------------------------
// Jacobian / affine point arithmetic (coordinates in Montgomery domain)
// ---------------------------------------------------------------------------

struct JPoint {
  U256 X, Y, Z;  // Z == 0 -> infinity
  bool inf() const { return is_zero(Z); }
};

struct APoint {
  U256 x, y;  // affine, Montgomery domain
};

struct Curve;
JPoint jac_add(const Curve& c, const JPoint& P, const JPoint& Q);
JPoint jac_double(const Curve& c, const JPoint& P);

constexpr int GW = 7;                 // static G window
constexpr int GTBL = 1 << (GW - 2);   // 32 odd multiples
constexpr int QW = 5;                 // per-signature window
constexpr int QTBL = 1 << (QW - 2);   // 8 odd multiples
constexpr int WNAF_MAX = 260;

struct Curve {
  Mont fp, fn;
  U256 a_m, b_m;
  bool a_zero = false, a_m3 = false;
  U256 sqrt_e;   // (p+1)/4, plain
  JPoint g;      // generator, Montgomery Jacobian (Z = 1_m)

  // GLV plane (secp256k1 only)
  bool has_glv = false;
  U256 glv_lambda, glv_g1, glv_g2, glv_mb1, glv_mb2;  // plain
  U256 beta_m;   // field beta, Montgomery
  U256 half_n;   // n >> 1 (signed-half threshold)

  // static affine wNAF tables: odd multiples (2i+1)G and (2i+1)phi(G)
  APoint gtab[GTBL], phigtab[GTBL];
  std::once_flag gtab_once;
};

JPoint jac_double(const Curve& c, const JPoint& P) {
  if (P.inf() || is_zero(P.Y)) return JPoint{};
  const Mont& f = c.fp;
  JPoint R;
  if (c.a_zero) {
    // dbl-2009-l: 2M + 5S
    U256 A = f.sqr(P.X);
    U256 B = f.sqr(P.Y);
    U256 C = f.sqr(B);
    U256 t = f.add(P.X, B);
    U256 D = f.sub(f.sub(f.sqr(t), A), C);
    D = f.add(D, D);
    U256 E = f.add(f.add(A, A), A);
    U256 F = f.sqr(E);
    R.X = f.sub(F, f.add(D, D));
    U256 C8 = f.add(C, C);
    C8 = f.add(C8, C8);
    C8 = f.add(C8, C8);
    R.Y = f.sub(f.mul(E, f.sub(D, R.X)), C8);
    U256 yz = f.mul(P.Y, P.Z);
    R.Z = f.add(yz, yz);
    return R;
  }
  if (c.a_m3) {
    // dbl-2001-b: 3M + 5S
    U256 delta = f.sqr(P.Z);
    U256 gamma = f.sqr(P.Y);
    U256 beta = f.mul(P.X, gamma);
    U256 t = f.mul(f.sub(P.X, delta), f.add(P.X, delta));
    U256 alpha = f.add(f.add(t, t), t);
    U256 beta4 = f.add(beta, beta);
    beta4 = f.add(beta4, beta4);
    R.X = f.sub(f.sqr(alpha), f.add(beta4, beta4));
    U256 zy = f.add(P.Y, P.Z);
    R.Z = f.sub(f.sub(f.sqr(zy), gamma), delta);
    U256 g2 = f.sqr(gamma);
    U256 g8 = f.add(g2, g2);
    g8 = f.add(g8, g8);
    g8 = f.add(g8, g8);
    R.Y = f.sub(f.mul(alpha, f.sub(beta4, R.X)), g8);
    return R;
  }
  // generic a
  U256 YY = f.sqr(P.Y);
  U256 S = f.mul(P.X, YY);
  S = f.add(S, S);
  S = f.add(S, S);
  U256 XX = f.sqr(P.X);
  U256 ZZ = f.sqr(P.Z);
  U256 M = f.add(f.add(f.add(XX, XX), XX), f.mul(c.a_m, f.sqr(ZZ)));
  U256 MM = f.sqr(M);
  R.X = f.sub(MM, f.add(S, S));
  U256 YYYY = f.sqr(YY);
  U256 y8 = f.add(YYYY, YYYY);
  y8 = f.add(y8, y8);
  y8 = f.add(y8, y8);
  R.Y = f.sub(f.mul(M, f.sub(S, R.X)), y8);
  U256 two_y = f.add(P.Y, P.Y);
  R.Z = f.mul(two_y, P.Z);
  return R;
}

JPoint jac_add(const Curve& c, const JPoint& P, const JPoint& Q) {
  if (P.inf()) return Q;
  if (Q.inf()) return P;
  const Mont& f = c.fp;
  U256 Z1Z1 = f.sqr(P.Z);
  U256 Z2Z2 = f.sqr(Q.Z);
  U256 U1 = f.mul(P.X, Z2Z2);
  U256 U2 = f.mul(Q.X, Z1Z1);
  U256 S1 = f.mul(f.mul(P.Y, Q.Z), Z2Z2);
  U256 S2 = f.mul(f.mul(Q.Y, P.Z), Z1Z1);
  U256 H = f.sub(U2, U1);
  U256 R = f.sub(S2, S1);
  if (is_zero(H)) {
    if (is_zero(R)) return jac_double(c, P);
    return JPoint{};  // P == -Q
  }
  U256 HH = f.sqr(H);
  U256 HHH = f.mul(H, HH);
  U256 V = f.mul(U1, HH);
  JPoint out;
  U256 RR = f.sqr(R);
  out.X = f.sub(f.sub(RR, HHH), f.add(V, V));
  out.Y = f.sub(f.mul(R, f.sub(V, out.X)), f.mul(S1, HHH));
  out.Z = f.mul(f.mul(P.Z, Q.Z), H);
  return out;
}

// P (Jacobian) + A (affine, negate_y selects -A): 8M + 3S mixed add
JPoint jac_madd(const Curve& c, const JPoint& P, const APoint& A,
                bool negate_y) {
  const Mont& f = c.fp;
  U256 ay = negate_y ? f.neg(A.y) : A.y;
  if (P.inf()) {
    JPoint R;
    R.X = A.x;
    R.Y = ay;
    R.Z = f.one_m;
    return R;
  }
  U256 Z1Z1 = f.sqr(P.Z);
  U256 U2 = f.mul(A.x, Z1Z1);
  U256 S2 = f.mul(ay, f.mul(P.Z, Z1Z1));
  U256 H = f.sub(U2, P.X);
  U256 R = f.sub(S2, P.Y);
  if (is_zero(H)) {
    if (is_zero(R)) return jac_double(c, P);
    return JPoint{};  // P == -A
  }
  U256 HH = f.sqr(H);
  U256 HHH = f.mul(H, HH);
  U256 V = f.mul(P.X, HH);
  JPoint out;
  U256 RR = f.sqr(R);
  out.X = f.sub(f.sub(RR, HHH), f.add(V, V));
  out.Y = f.sub(f.mul(R, f.sub(V, out.X)), f.mul(P.Y, HHH));
  out.Z = f.mul(P.Z, H);
  return out;
}

// normalise n Jacobian points to affine with ONE field inversion; points at
// infinity produce (0, 0) and ok[i] = false (when ok != nullptr)
void batch_normalize(const Curve& c, const JPoint* pts, int n, APoint* out,
                     bool* ok) {
  const Mont& f = c.fp;
  U256* zs = new U256[n];
  for (int i = 0; i < n; ++i) zs[i] = pts[i].Z;
  batch_inv(f, zs, n);
  for (int i = 0; i < n; ++i) {
    if (pts[i].inf()) {
      out[i] = APoint{};
      if (ok) ok[i] = false;
      continue;
    }
    U256 zi2 = f.sqr(zs[i]);
    out[i].x = f.mul(pts[i].X, zi2);
    out[i].y = f.mul(pts[i].Y, f.mul(zi2, zs[i]));
    if (ok) ok[i] = true;
  }
  delete[] zs;
}

// ---------------------------------------------------------------------------
// wNAF
// ---------------------------------------------------------------------------

// signed windowed NAF of k (k plain, any magnitude); returns digit count.
// negate flips every digit (folds the GLV half sign into the encoding).
int wnaf_encode(const U256& k, int w, bool negate, int8_t* out) {
  U256 x = k;
  int len = 0;
  const uint64_t mask = (1ull << w) - 1;
  const int64_t half = 1ll << (w - 1);
  while (!is_zero(x)) {
    int64_t d = 0;
    if (x.w[0] & 1) {
      d = (int64_t)(x.w[0] & mask);
      if (d > half) d -= (int64_t)1 << w;
      U256 dd;
      if (d > 0) {
        dd.w[0] = (uint64_t)d;
        sub_bb(x, dd, x);
      } else {
        dd.w[0] = (uint64_t)(-d);
        add_cc(x, dd, x);
      }
    }
    out[len++] = (int8_t)(negate ? -d : d);
    shr1(x);
  }
  return len;
}

// ---------------------------------------------------------------------------
// curve singletons
// ---------------------------------------------------------------------------

U256 hex_u256(const char* h) {  // 64 hex chars, big-endian
  uint8_t b[32];
  for (int i = 0; i < 32; ++i) {
    auto nib = [](char ch) -> uint8_t {
      return ch <= '9' ? ch - '0' : (ch | 32) - 'a' + 10;
    };
    b[i] = (uint8_t)((nib(h[2 * i]) << 4) | nib(h[2 * i + 1]));
  }
  return from_be(b);
}

Curve* make_curve(const char* p, const char* n, const char* a, const char* b,
                  const char* gx, const char* gy) {
  Curve* c = new Curve();
  c->fp.init(hex_u256(p));
  c->fn.init(hex_u256(n));
  U256 av = hex_u256(a);
  c->a_zero = is_zero(av);
  U256 p3;
  U256 three;
  three.w[0] = 3;
  sub_bb(c->fp.mod, three, p3);
  c->a_m3 = cmp(av, p3) == 0;
  c->a_m = c->fp.to_mont(av);
  c->b_m = c->fp.to_mont(hex_u256(b));
  // (p+1)/4
  U256 p1 = c->fp.mod;
  U256 one;
  one.w[0] = 1;
  add_cc(p1, one, p1);
  for (int s = 0; s < 2; ++s) shr1(p1);
  c->sqrt_e = p1;
  c->g.X = c->fp.to_mont(hex_u256(gx));
  c->g.Y = c->fp.to_mont(hex_u256(gy));
  c->g.Z = c->fp.one_m;
  c->half_n = c->fn.mod;
  shr1(c->half_n);
  return c;
}

// static G / phi(G) odd-multiple tables (one inversion, lazy)
void build_gtab(Curve& c) {
  JPoint jt[GTBL];
  jt[0] = c.g;
  JPoint g2 = jac_double(c, c.g);
  for (int i = 1; i < GTBL; ++i) jt[i] = jac_add(c, jt[i - 1], g2);
  batch_normalize(c, jt, GTBL, c.gtab, nullptr);
  if (c.has_glv) {
    for (int i = 0; i < GTBL; ++i) {
      c.phigtab[i].x = c.fp.mul(c.gtab[i].x, c.beta_m);
      c.phigtab[i].y = c.gtab[i].y;
    }
  }
}

Curve& secp256k1() {
  static Curve* c = [] {
    Curve* cv = make_curve(
        "fffffffffffffffffffffffffffffffffffffffffffffffffffffffefffffc2f",
        "fffffffffffffffffffffffffffffffebaaedce6af48a03bbfd25e8cd0364141",
        "0000000000000000000000000000000000000000000000000000000000000000",
        "0000000000000000000000000000000000000000000000000000000000000007",
        "79be667ef9dcbbac55a06295ce870b07029bfcdb2dce28d959f2815b16f81798",
        "483ada7726a3c4655da4fbfc0e1108a8fd17b448a68554199c47d08ffb10d4b8");
    // GLV endomorphism constants (crypto/refimpl.py:340-346 — lambda/beta
    // published curve parameters, g1/g2 the 384-bit mul-shift rounding
    // constants, mb1/mb2 = -b1/-b2 mod n)
    cv->has_glv = true;
    cv->glv_lambda = hex_u256(
        "5363ad4cc05c30e0a5261c028812645a122e22ea20816678df02967c1b23bd72");
    cv->beta_m = cv->fp.to_mont(hex_u256(
        "7ae96a2b657c07106e64479eac3434e99cf0497512f58995c1396c28719501ee"));
    cv->glv_mb1 = hex_u256(
        "00000000000000000000000000000000e4437ed6010e88286f547fa90abfe4c3");
    cv->glv_mb2 = hex_u256(
        "fffffffffffffffffffffffffffffffe8a280ac50774346dd765cda83db1562c");
    cv->glv_g1 = hex_u256(
        "3086d221a7d46bcde86c90e49284eb153daa8a1471e8ca7fe893209a45dbb031");
    cv->glv_g2 = hex_u256(
        "e4437ed6010e88286f547fa90abfe4c4221208ac9df506c61571b4ae8ac47f71");
    return cv;
  }();
  return *c;
}

Curve& sm2p256v1() {
  static Curve* c = make_curve(
      "fffffffeffffffffffffffffffffffffffffffff00000000ffffffffffffffff",
      "fffffffeffffffffffffffffffffffff7203df6b21c6052b53bbf40939d54123",
      "fffffffeffffffffffffffffffffffffffffffff00000000fffffffffffffffc",
      "28e9fa9e9d9f5e344d5a9e4bcf6509a7f39789f515ab8f92ddbcbd414d940e93",
      "32c4ae2c1f1981195f9904466a39c9948fe30bbff2660be1715a4589334c74c7",
      "bc3736a2f4f6779c59bdcee36b692153d0a9877cc62a474002df32e52139f0a0");
  return *c;
}

Curve& by_id(int id) { return id == 0 ? secp256k1() : sm2p256v1(); }

// shared checks: 1 <= r,s < n
bool scalar_ok(const Curve& c, const U256& r, const U256& s) {
  return !is_zero(r) && !is_zero(s) && cmp(r, c.fn.mod) < 0 &&
         cmp(s, c.fn.mod) < 0;
}

// pub (plain, implicitly reduced mod p like the oracle) -> Montgomery
// Jacobian; false when not on the curve
bool load_pub(Curve& c, const U256& qx, const U256& qy, JPoint* out) {
  U256 x = c.fp.reduce(qx), y = c.fp.reduce(qy);
  U256 xm = c.fp.to_mont(x), ym = c.fp.to_mont(y);
  U256 rhs = c.fp.add(c.fp.mul(c.fp.sqr(xm), xm), c.b_m);
  if (!c.a_zero) rhs = c.fp.add(rhs, c.fp.mul(c.a_m, xm));
  if (cmp(c.fp.sqr(ym), rhs) != 0) return false;
  out->X = xm;
  out->Y = ym;
  out->Z = c.fp.one_m;
  return true;
}

// x (affine plain, < p) mod n — p < 2n for both curves
U256 mod_n(const Curve& c, const U256& x) {
  if (cmp(x, c.fn.mod) >= 0) {
    U256 r;
    sub_bb(x, c.fn.mod, r);
    return r;
  }
  return x;
}

// ---------------------------------------------------------------------------
// GLV split (secp256k1): k -> signed halves (m1, neg1), (m2, neg2) with
// (-1)^neg1 * m1 + (-1)^neg2 * m2 * lambda == k (mod n), |m_i| <~ 2^129.
// Exactly refimpl.glv_split + the signed mapping min(k_i, n - k_i).
// ---------------------------------------------------------------------------

void glv_split(const Curve& c, const U256& k, U256& m1, bool& neg1,
               U256& m2, bool& neg2) {
  uint64_t wide[8];
  U256 c1, c2;
  mul_wide(k, c.glv_g1, wide);
  c1.w[0] = wide[6];
  c1.w[1] = wide[7];
  mul_wide(k, c.glv_g2, wide);
  c2.w[0] = wide[6];
  c2.w[1] = wide[7];
  const Mont& fn = c.fn;
  U256 k2 = fn.add(fn.mulmod(c1, c.glv_mb1), fn.mulmod(c2, c.glv_mb2));
  U256 k1 = fn.sub(k, fn.mulmod(k2, c.glv_lambda));
  neg1 = cmp(k1, c.half_n) > 0;
  m1 = neg1 ? fn.neg(k1) : k1;
  neg2 = cmp(k2, c.half_n) > 0;
  m2 = neg2 ? fn.neg(k2) : k2;
}

// ---------------------------------------------------------------------------
// batch double-scalar multiplication contexts
// ---------------------------------------------------------------------------

// one signature's ladder inputs: up to 4 wNAF planes (G, phiG, Q, phiQ for
// GLV; G, Q for plain curves) + the per-signature affine Q tables
struct LadderCtx {
  bool valid = false;
  int8_t dG[WNAF_MAX], dPG[WNAF_MAX], dQ[WNAF_MAX], dPQ[WNAF_MAX];
  int lG = 0, lPG = 0, lQ = 0, lPQ = 0;
  APoint qtab[QTBL];     // odd multiples of Q, affine
  APoint phiqtab[QTBL];  // phi(odd multiples), affine (GLV only)
};

// Phase A helper: Jacobian odd multiples of Q for later batch-normalise
void q_multiples(const Curve& c, const JPoint& Q, JPoint* out) {
  out[0] = Q;
  JPoint q2 = jac_double(c, Q);
  for (int i = 1; i < QTBL; ++i) out[i] = jac_add(c, out[i - 1], q2);
}

// Phase B: run one ladder (acc = sum of planes) given affine tables
JPoint run_ladder(const Curve& c, const LadderCtx& L) {
  int len = L.lG;
  if (L.lPG > len) len = L.lPG;
  if (L.lQ > len) len = L.lQ;
  if (L.lPQ > len) len = L.lPQ;
  JPoint acc{};
  for (int i = len - 1; i >= 0; --i) {
    if (!acc.inf()) acc = jac_double(c, acc);
    int8_t d;
    if (i < L.lG && (d = L.dG[i]) != 0)
      acc = jac_madd(c, acc, c.gtab[(d > 0 ? d : -d) >> 1], d < 0);
    if (i < L.lPG && (d = L.dPG[i]) != 0)
      acc = jac_madd(c, acc, c.phigtab[(d > 0 ? d : -d) >> 1], d < 0);
    if (i < L.lQ && (d = L.dQ[i]) != 0)
      acc = jac_madd(c, acc, L.qtab[(d > 0 ? d : -d) >> 1], d < 0);
    if (i < L.lPQ && (d = L.dPQ[i]) != 0)
      acc = jac_madd(c, acc, L.phiqtab[(d > 0 ? d : -d) >> 1], d < 0);
  }
  return acc;
}

// fill a ladder context's scalar planes for k1*G + k2*Q on curve c.
// GLV curves split both scalars; plain curves use full-width planes.
void fill_scalars(const Curve& c, const U256& k1, const U256& k2,
                  LadderCtx& L) {
  if (c.has_glv) {
    U256 a1, a2, b1, b2;
    bool s1, s2, t1, t2;
    glv_split(c, k1, a1, s1, a2, s2);
    glv_split(c, k2, b1, t1, b2, t2);
    L.lG = wnaf_encode(a1, GW, s1, L.dG);
    L.lPG = wnaf_encode(a2, GW, s2, L.dPG);
    L.lQ = wnaf_encode(b1, QW, t1, L.dQ);
    L.lPQ = wnaf_encode(b2, QW, t2, L.dPQ);
  } else {
    L.lG = wnaf_encode(k1, GW, false, L.dG);
    L.lPG = 0;
    L.lQ = wnaf_encode(k2, QW, false, L.dQ);
    L.lPQ = 0;
  }
}

// build the affine Q tables for a chunk with ONE shared inversion:
// jtabs[i*QTBL + t] are the Jacobian odd multiples of sig i's point
void finish_q_tables(const Curve& c, JPoint* jtabs, LadderCtx* ctxs,
                     int count) {
  APoint* flat = new APoint[count * QTBL];
  batch_normalize(c, jtabs, count * QTBL, flat, nullptr);
  for (int i = 0; i < count; ++i) {
    if (!ctxs[i].valid) continue;
    for (int t = 0; t < QTBL; ++t) {
      ctxs[i].qtab[t] = flat[i * QTBL + t];
      if (c.has_glv) {
        ctxs[i].phiqtab[t].x = c.fp.mul(flat[i * QTBL + t].x, c.beta_m);
        ctxs[i].phiqtab[t].y = flat[i * QTBL + t].y;
      }
    }
  }
  delete[] flat;
}

constexpr int CHUNK = 128;

}  // namespace

extern "C" {

int ncrypto_available(void) { return 1; }

#ifndef FBTPU_SRC_HASH
#define FBTPU_SRC_HASH "unstamped"
#endif
// sha256 of the source this binary was built from (see native/Makefile);
// Python loaders compare against the checked-in .cpp and refuse a
// drifted binary so stale consensus-critical semantics fail loudly
const char* ncrypto_src_hash(void) { return FBTPU_SRC_HASH; }

// All arrays are count rows of 32 big-endian bytes; ok_out: count bytes.
void ncrypto_ecdsa_verify_batch(int curve_id, uint64_t count,
                                const uint8_t* es, const uint8_t* rs,
                                const uint8_t* ss, const uint8_t* qxs,
                                const uint8_t* qys, uint8_t* ok_out) {
  Curve& c = by_id(curve_id);
  std::call_once(c.gtab_once, build_gtab, std::ref(c));
  LadderCtx* ctxs = new LadderCtx[CHUNK];
  JPoint* jtabs = new JPoint[CHUNK * QTBL];
  U256* sinv = new U256[CHUNK];
  U256* rvals = new U256[CHUNK];
  U256* evals = new U256[CHUNK];
  JPoint* results = new JPoint[CHUNK];
  APoint* aff = new APoint[CHUNK];
  bool* aok = new bool[CHUNK];
  for (uint64_t base = 0; base < count; base += CHUNK) {
    int m = (int)((count - base < CHUNK) ? count - base : CHUNK);
    // phase A: validate, collect s for batched inversion
    for (int i = 0; i < m; ++i) {
      uint64_t g = base + i;
      ok_out[g] = 0;
      ctxs[i] = LadderCtx{};
      sinv[i] = U256{};
      U256 r = from_be(rs + 32 * g), s = from_be(ss + 32 * g);
      if (!scalar_ok(c, r, s)) continue;
      JPoint Q;
      if (!load_pub(c, from_be(qxs + 32 * g), from_be(qys + 32 * g), &Q))
        continue;
      ctxs[i].valid = true;
      rvals[i] = r;
      evals[i] = mod_n(c, c.fn.reduce(from_be(es + 32 * g)));
      sinv[i] = c.fn.to_mont(s);
      q_multiples(c, Q, jtabs + i * QTBL);
    }
    batch_inv(c.fn, sinv, m);  // sinv[i] = (s^-1) Montgomery
    finish_q_tables(c, jtabs, ctxs, m);
    // phase B: scalars + ladders
    for (int i = 0; i < m; ++i) {
      results[i] = JPoint{};
      if (!ctxs[i].valid) continue;
      U256 u1 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(evals[i]), sinv[i]));
      U256 u2 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(rvals[i]), sinv[i]));
      fill_scalars(c, u1, u2, ctxs[i]);
      results[i] = run_ladder(c, ctxs[i]);
    }
    // phase C: one inversion for all affine x's, then the final compare
    batch_normalize(c, results, m, aff, aok);
    for (int i = 0; i < m; ++i) {
      if (!ctxs[i].valid || !aok[i]) continue;
      U256 x = c.fp.from_mont(aff[i].x);
      ok_out[base + i] = cmp(mod_n(c, x), rvals[i]) == 0;
    }
  }
  delete[] ctxs;
  delete[] jtabs;
  delete[] sinv;
  delete[] rvals;
  delete[] evals;
  delete[] results;
  delete[] aff;
  delete[] aok;
}

// vs: count bytes (recovery ids); pub_out: count rows of 64 bytes (x|y).
void ncrypto_ecdsa_recover_batch(int curve_id, uint64_t count,
                                 const uint8_t* es, const uint8_t* rs,
                                 const uint8_t* ss, const uint8_t* vs,
                                 uint8_t* pub_out, uint8_t* ok_out) {
  Curve& c = by_id(curve_id);
  std::call_once(c.gtab_once, build_gtab, std::ref(c));
  LadderCtx* ctxs = new LadderCtx[CHUNK];
  JPoint* jtabs = new JPoint[CHUNK * QTBL];
  U256* rinv = new U256[CHUNK];
  U256* svals = new U256[CHUNK];
  U256* evals = new U256[CHUNK];
  JPoint* results = new JPoint[CHUNK];
  APoint* aff = new APoint[CHUNK];
  bool* aok = new bool[CHUNK];
  for (uint64_t base = 0; base < count; base += CHUNK) {
    int m = (int)((count - base < CHUNK) ? count - base : CHUNK);
    for (int i = 0; i < m; ++i) {
      uint64_t g = base + i;
      ok_out[g] = 0;
      memset(pub_out + 64 * g, 0, 64);
      ctxs[i] = LadderCtx{};
      rinv[i] = U256{};
      U256 r = from_be(rs + 32 * g), s = from_be(ss + 32 * g);
      uint8_t v = vs[g];
      if (!scalar_ok(c, r, s)) continue;
      if ((v >> 1) >= 2) continue;  // x = r + (v>>1)*n >= 2n > p
      U256 x = r;
      if (v >> 1) {
        if (add_cc(r, c.fn.mod, x)) continue;  // overflowed 2^256
      }
      if (cmp(x, c.fp.mod) >= 0) continue;
      U256 xm = c.fp.to_mont(x);
      U256 ysq = c.fp.add(c.fp.mul(c.fp.sqr(xm), xm), c.b_m);
      if (!c.a_zero) ysq = c.fp.add(ysq, c.fp.mul(c.a_m, xm));
      U256 y = c.fp.pow_win4(ysq, c.sqrt_e);
      if (cmp(c.fp.sqr(y), ysq) != 0) continue;  // non-residue
      U256 y_plain = c.fp.from_mont(y);
      if ((y_plain.w[0] & 1) != (v & 1)) y = c.fp.neg(y);
      ctxs[i].valid = true;
      svals[i] = s;
      evals[i] = mod_n(c, c.fn.reduce(from_be(es + 32 * g)));
      rinv[i] = c.fn.to_mont(r);
      JPoint R;
      R.X = xm;
      R.Y = y;
      R.Z = c.fp.one_m;
      q_multiples(c, R, jtabs + i * QTBL);
    }
    batch_inv(c.fn, rinv, m);  // rinv[i] = (r^-1) Montgomery
    finish_q_tables(c, jtabs, ctxs, m);
    for (int i = 0; i < m; ++i) {
      results[i] = JPoint{};
      if (!ctxs[i].valid) continue;
      // u1 = -e/r, u2 = s/r (mod n)
      U256 u1 = c.fn.from_mont(
          c.fn.mul(c.fn.neg(c.fn.to_mont(evals[i])), rinv[i]));
      U256 u2 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(svals[i]), rinv[i]));
      fill_scalars(c, u1, u2, ctxs[i]);
      results[i] = run_ladder(c, ctxs[i]);
    }
    batch_normalize(c, results, m, aff, aok);
    for (int i = 0; i < m; ++i) {
      if (!ctxs[i].valid || !aok[i]) continue;
      to_be(c.fp.from_mont(aff[i].x), pub_out + 64 * (base + i));
      to_be(c.fp.from_mont(aff[i].y), pub_out + 64 * (base + i) + 32);
      ok_out[base + i] = 1;
    }
  }
  delete[] ctxs;
  delete[] jtabs;
  delete[] rinv;
  delete[] svals;
  delete[] evals;
  delete[] results;
  delete[] aff;
  delete[] aok;
}

// Batched signing. The nonce k comes from the CALLER (crypto/refimpl.py's
// RFC 6979 derivation — HMAC-SHA256 stays in Python where hashlib is
// already native); this routine does the EC work and the signature
// algebra, byte-exact with refimpl.ecdsa_sign given the same k. A lane
// whose r or s degenerates to zero (never in practice for RFC 6979
// nonces) reports ok=0 and the caller falls back to the oracle.
// es/ds/ks: count rows of 32 BE bytes (digest, secret, nonce);
// out_r/out_s: 32-byte rows; out_v: count bytes; ok_out: count bytes.
void ncrypto_ecdsa_sign_batch(int curve_id, uint64_t count,
                              const uint8_t* es, const uint8_t* ds,
                              const uint8_t* ks, uint8_t* out_r,
                              uint8_t* out_s, uint8_t* out_v,
                              uint8_t* ok_out) {
  Curve& c = by_id(curve_id);
  std::call_once(c.gtab_once, build_gtab, std::ref(c));
  // suite.sign() calls with count=1 (one signature per consensus packet):
  // size the scratch to the actual lane count, not the batch chunk
  const int cap = (int)(count < CHUNK ? (count ? count : 1) : CHUNK);
  LadderCtx* ctxs = new LadderCtx[cap];
  U256* kinv = new U256[cap];
  JPoint* results = new JPoint[cap];
  APoint* aff = new APoint[cap];
  bool* aok = new bool[cap];
  U256 zero;
  for (uint64_t base = 0; base < count; base += CHUNK) {
    int m = (int)((count - base < CHUNK) ? count - base : CHUNK);
    for (int i = 0; i < m; ++i) {
      uint64_t g = base + i;
      ok_out[g] = 0;
      memset(out_r + 32 * g, 0, 32);
      memset(out_s + 32 * g, 0, 32);
      out_v[g] = 0;
      ctxs[i] = LadderCtx{};
      kinv[i] = U256{};
      results[i] = JPoint{};
      U256 k = from_be(ks + 32 * g);
      if (is_zero(k) || cmp(k, c.fn.mod) >= 0) continue;
      ctxs[i].valid = true;
      kinv[i] = c.fn.to_mont(k);
      fill_scalars(c, k, zero, ctxs[i]);  // k*G (Q planes empty)
      results[i] = run_ladder(c, ctxs[i]);
    }
    batch_inv(c.fn, kinv, m);  // kinv[i] = (k^-1) Montgomery
    batch_normalize(c, results, m, aff, aok);
    for (int i = 0; i < m; ++i) {
      if (!ctxs[i].valid || !aok[i]) continue;
      uint64_t g = base + i;
      U256 e = mod_n(c, c.fn.reduce(from_be(es + 32 * g)));
      U256 d = from_be(ds + 32 * g);
      U256 rx = c.fp.from_mont(aff[i].x);
      U256 r = mod_n(c, rx);
      if (is_zero(r)) continue;
      // s = k^-1 (e + r*d) mod n
      U256 rd = c.fn.from_mont(
          c.fn.mul(c.fn.to_mont(r), c.fn.to_mont(c.fn.reduce(d))));
      U256 erd = c.fn.add(e, rd);
      U256 s = c.fn.from_mont(c.fn.mul(c.fn.to_mont(erd), kinv[i]));
      if (is_zero(s)) continue;
      uint8_t v = (uint8_t)(c.fp.from_mont(aff[i].y).w[0] & 1);
      if (cmp(s, c.half_n) > 0) {  // low-s normal form (refimpl parity)
        s = c.fn.neg(s);
        v ^= 1;
      }
      to_be(r, out_r + 32 * g);
      to_be(s, out_s + 32 * g);
      out_v[g] = v;
      ok_out[g] = 1;
    }
  }
  delete[] ctxs;
  delete[] kinv;
  delete[] results;
  delete[] aff;
  delete[] aok;
}

// SM2 signing (GB/T 32918): r = (e + x(kG)) mod n, s = (1+d)^-1 (k - r d).
void ncrypto_sm2_sign_batch(uint64_t count, const uint8_t* es,
                            const uint8_t* ds, const uint8_t* ks,
                            uint8_t* out_r, uint8_t* out_s,
                            uint8_t* ok_out) {
  Curve& c = sm2p256v1();
  std::call_once(c.gtab_once, build_gtab, std::ref(c));
  const int cap = (int)(count < CHUNK ? (count ? count : 1) : CHUNK);
  LadderCtx* ctxs = new LadderCtx[cap];
  U256* dinv = new U256[cap];
  JPoint* results = new JPoint[cap];
  APoint* aff = new APoint[cap];
  bool* aok = new bool[cap];
  U256 zero;
  for (uint64_t base = 0; base < count; base += CHUNK) {
    int m = (int)((count - base < CHUNK) ? count - base : CHUNK);
    for (int i = 0; i < m; ++i) {
      uint64_t g = base + i;
      ok_out[g] = 0;
      memset(out_r + 32 * g, 0, 32);
      memset(out_s + 32 * g, 0, 32);
      ctxs[i] = LadderCtx{};
      dinv[i] = U256{};
      results[i] = JPoint{};
      U256 k = from_be(ks + 32 * g);
      if (is_zero(k) || cmp(k, c.fn.mod) >= 0) continue;
      U256 one;
      one.w[0] = 1;
      U256 d1 = c.fn.add(c.fn.reduce(from_be(ds + 32 * g)), one);
      if (is_zero(d1)) continue;  // d == n-1: (1+d) not invertible
      ctxs[i].valid = true;
      dinv[i] = c.fn.to_mont(d1);
      fill_scalars(c, k, zero, ctxs[i]);
      results[i] = run_ladder(c, ctxs[i]);
    }
    batch_inv(c.fn, dinv, m);  // dinv[i] = ((1+d)^-1) Montgomery
    batch_normalize(c, results, m, aff, aok);
    for (int i = 0; i < m; ++i) {
      if (!ctxs[i].valid || !aok[i]) continue;
      uint64_t g = base + i;
      U256 e = mod_n(c, c.fn.reduce(from_be(es + 32 * g)));
      U256 k = from_be(ks + 32 * g);
      U256 d = c.fn.reduce(from_be(ds + 32 * g));
      U256 px = mod_n(c, c.fp.from_mont(aff[i].x));
      U256 r = c.fn.add(e, px);
      if (is_zero(r)) continue;
      if (is_zero(c.fn.sub(c.fn.neg(r), k))) continue;  // r + k == n
      // s = (1+d)^-1 (k - r*d) mod n
      U256 rd = c.fn.from_mont(
          c.fn.mul(c.fn.to_mont(r), c.fn.to_mont(d)));
      U256 krd = c.fn.sub(k, rd);
      U256 s = c.fn.from_mont(c.fn.mul(c.fn.to_mont(krd), dinv[i]));
      if (is_zero(s)) continue;
      to_be(r, out_r + 32 * g);
      to_be(s, out_s + 32 * g);
      ok_out[g] = 1;
    }
  }
  delete[] ctxs;
  delete[] dinv;
  delete[] results;
  delete[] aff;
  delete[] aok;
}

void ncrypto_sm2_verify_batch(uint64_t count, const uint8_t* es,
                              const uint8_t* rs, const uint8_t* ss,
                              const uint8_t* qxs, const uint8_t* qys,
                              uint8_t* ok_out) {
  Curve& c = sm2p256v1();
  std::call_once(c.gtab_once, build_gtab, std::ref(c));
  LadderCtx* ctxs = new LadderCtx[CHUNK];
  JPoint* jtabs = new JPoint[CHUNK * QTBL];
  U256* rvals = new U256[CHUNK];
  U256* evals = new U256[CHUNK];
  U256* svals = new U256[CHUNK];
  U256* tvals = new U256[CHUNK];
  JPoint* results = new JPoint[CHUNK];
  APoint* aff = new APoint[CHUNK];
  bool* aok = new bool[CHUNK];
  for (uint64_t base = 0; base < count; base += CHUNK) {
    int m = (int)((count - base < CHUNK) ? count - base : CHUNK);
    for (int i = 0; i < m; ++i) {
      uint64_t g = base + i;
      ok_out[g] = 0;
      ctxs[i] = LadderCtx{};
      U256 r = from_be(rs + 32 * g), s = from_be(ss + 32 * g);
      if (!scalar_ok(c, r, s)) continue;
      JPoint Q;
      if (!load_pub(c, from_be(qxs + 32 * g), from_be(qys + 32 * g), &Q))
        continue;
      U256 t = c.fn.add(r, s);  // r, s < n: fn.add reduces mod n
      if (is_zero(t)) continue;
      ctxs[i].valid = true;
      rvals[i] = r;
      svals[i] = s;
      tvals[i] = t;
      evals[i] = mod_n(c, c.fn.reduce(from_be(es + 32 * g)));
      q_multiples(c, Q, jtabs + i * QTBL);
    }
    finish_q_tables(c, jtabs, ctxs, m);
    for (int i = 0; i < m; ++i) {
      results[i] = JPoint{};
      if (!ctxs[i].valid) continue;
      fill_scalars(c, svals[i], tvals[i], ctxs[i]);  // s*G + t*Q
      results[i] = run_ladder(c, ctxs[i]);
    }
    batch_normalize(c, results, m, aff, aok);
    for (int i = 0; i < m; ++i) {
      if (!ctxs[i].valid || !aok[i]) continue;
      U256 x = c.fp.from_mont(aff[i].x);
      // (e + x) mod n == r
      U256 lhs = c.fn.add(evals[i], mod_n(c, x));
      ok_out[base + i] = cmp(lhs, rvals[i]) == 0;
    }
  }
  delete[] ctxs;
  delete[] jtabs;
  delete[] rvals;
  delete[] evals;
  delete[] svals;
  delete[] tvals;
  delete[] results;
  delete[] aff;
  delete[] aok;
}

}  // extern "C"
