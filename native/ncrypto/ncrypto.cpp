// ncrypto — native host-path EC signature engine for fisco-bcos-tpu.
//
// Reference counterpart: the WeDPR FFI natives behind
// /root/reference/bcos-crypto/bcos-crypto/signature/secp256k1/
// Secp256k1Crypto.cpp:40,57,85 and signature/sm2/SM2Crypto.h — the
// reference's per-signature hot functions are native; this framework's
// DEVICE path batches them on TPU (ops/ec.py), and this library is the
// native floor for the HOST path (sub-threshold batches, no-accelerator
// deployments, ingest fallback), ~100x the pure-Python oracle.
//
// Determinism contract: results must match crypto/refimpl.py exactly —
// including its edge semantics (coordinates implicitly reduced mod p, the
// final verify comparison mod n, recover's x = r + (v>>1)*n overflow
// behavior). tests/test_nativeec.py holds the equivalence suite.
//
// Implementation: 4x64-limb integers, Montgomery (CIOS) multiplication for
// all four moduli, branchy Jacobian point arithmetic (host code — no
// branch-free discipline needed; inputs are public), 4-bit-window Shamir
// double-scalar multiplication with a lazily built static G table.

#include <cstdint>
#include <cstring>
#include <mutex>

namespace {

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};
};

inline bool is_zero(const U256& a) {
  return !(a.w[0] | a.w[1] | a.w[2] | a.w[3]);
}

inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

inline uint64_t add_cc(const U256& a, const U256& b, U256& r) {
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 s = (unsigned __int128)a.w[i] + b.w[i] + c;
    r.w[i] = (uint64_t)s;
    c = s >> 64;
  }
  return (uint64_t)c;
}

inline uint64_t sub_bb(const U256& a, const U256& b, U256& r) {
  unsigned __int128 br = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a.w[i] - b.w[i] - br;
    r.w[i] = (uint64_t)d;
    br = (d >> 64) ? 1 : 0;
  }
  return (uint64_t)br;
}

U256 from_be(const uint8_t* b) {
  U256 r;
  for (int i = 0; i < 32; ++i)
    r.w[(31 - i) / 8] |= (uint64_t)b[i] << (((31 - i) % 8) * 8);
  return r;
}

void to_be(const U256& v, uint8_t* out) {
  for (int i = 0; i < 32; ++i)
    out[i] = (uint8_t)(v.w[(31 - i) / 8] >> (((31 - i) % 8) * 8));
}

inline bool bit(const U256& v, int i) { return (v.w[i / 64] >> (i % 64)) & 1; }

int bitlen(const U256& v) {
  for (int i = 3; i >= 0; --i)
    if (v.w[i]) return i * 64 + 64 - __builtin_clzll(v.w[i]);
  return 0;
}

// ---------------------------------------------------------------------------
// Montgomery field
// ---------------------------------------------------------------------------

struct Mont {
  U256 mod;
  uint64_t n0inv = 0;  // -mod^-1 mod 2^64
  U256 rr;             // 2^512 mod mod (to_mont multiplier)
  U256 one_m;          // 2^256 mod mod (Montgomery 1)

  void init(const U256& m) {
    mod = m;
    uint64_t x = m.w[0];  // Newton: x := x*(2 - m*x), doubles precision
    for (int i = 0; i < 6; ++i) x *= 2 - m.w[0] * x;
    n0inv = ~x + 1;  // -(m^-1) mod 2^64
    U256 v;
    v.w[0] = 1;
    for (int i = 0; i < 256; ++i) v = dbl_mod(v);
    one_m = v;
    for (int i = 0; i < 256; ++i) v = dbl_mod(v);
    rr = v;
  }

  U256 dbl_mod(const U256& a) const {
    U256 r;
    uint64_t c = add_cc(a, a, r);
    U256 t;
    if (c || cmp(r, mod) >= 0) {
      sub_bb(r, mod, t);
      return t;
    }
    return r;
  }

  U256 add(const U256& a, const U256& b) const {
    U256 r, t;
    uint64_t c = add_cc(a, b, r);
    if (c || cmp(r, mod) >= 0) {
      sub_bb(r, mod, t);
      return t;
    }
    return r;
  }

  U256 sub(const U256& a, const U256& b) const {
    U256 r, t;
    if (sub_bb(a, b, r)) {
      add_cc(r, mod, t);
      return t;
    }
    return r;
  }

  U256 neg(const U256& a) const {
    if (is_zero(a)) return a;
    U256 r;
    sub_bb(mod, a, r);
    return r;
  }

  // CIOS Montgomery multiplication
  U256 mul(const U256& a, const U256& b) const {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
      unsigned __int128 carry = 0;
      for (int j = 0; j < 4; ++j) {
        unsigned __int128 cur =
            (unsigned __int128)a.w[i] * b.w[j] + t[j] + carry;
        t[j] = (uint64_t)cur;
        carry = cur >> 64;
      }
      unsigned __int128 cur = (unsigned __int128)t[4] + carry;
      t[4] = (uint64_t)cur;
      t[5] = (uint64_t)(cur >> 64);

      uint64_t m = t[0] * n0inv;
      carry = 0;
      unsigned __int128 c0 = (unsigned __int128)m * mod.w[0] + t[0];
      carry = c0 >> 64;
      for (int j = 1; j < 4; ++j) {
        unsigned __int128 cur2 =
            (unsigned __int128)m * mod.w[j] + t[j] + carry;
        t[j - 1] = (uint64_t)cur2;
        carry = cur2 >> 64;
      }
      unsigned __int128 c4 = (unsigned __int128)t[4] + carry;
      t[3] = (uint64_t)c4;
      t[4] = t[5] + (uint64_t)(c4 >> 64);
      t[5] = 0;
    }
    U256 r;
    memcpy(r.w, t, 32);
    if (t[4] || cmp(r, mod) >= 0) {
      U256 o;
      sub_bb(r, mod, o);
      return o;
    }
    return r;
  }

  U256 to_mont(const U256& a) const { return mul(a, rr); }
  U256 from_mont(const U256& a) const {
    U256 one;
    one.w[0] = 1;
    return mul(a, one);
  }
  U256 sqr(const U256& a) const { return mul(a, a); }

  // a^e (a Montgomery, e plain), square-and-multiply MSB-first
  U256 pow(const U256& a, const U256& e) const {
    U256 acc = one_m;
    int n = bitlen(e);
    for (int i = n - 1; i >= 0; --i) {
      acc = sqr(acc);
      if (bit(e, i)) acc = mul(acc, a);
    }
    return acc;
  }

  U256 inv(const U256& a) const {  // Fermat (mod prime)
    U256 e = mod;
    U256 two;
    two.w[0] = 2;
    sub_bb(e, two, e);
    return pow(a, e);
  }

  // plain value (possibly >= mod, < 2^256) -> canonical plain
  U256 reduce(const U256& a) const {
    if (cmp(a, mod) >= 0) {
      U256 r;
      sub_bb(a, mod, r);
      if (cmp(r, mod) >= 0) {  // inputs < 2^256 < 2*mod for our moduli,
        U256 r2;               // but stay safe
        sub_bb(r, mod, r2);
        return r2;
      }
      return r;
    }
    return a;
  }
};

// ---------------------------------------------------------------------------
// Jacobian point arithmetic (coordinates in Montgomery domain)
// ---------------------------------------------------------------------------

struct JPoint {
  U256 X, Y, Z;  // Z == 0 -> infinity
  bool inf() const { return is_zero(Z); }
};

struct Curve {
  Mont fp, fn;
  U256 a_m, b_m;
  bool a_zero = false, a_m3 = false;
  U256 sqrt_e;   // (p+1)/4, plain
  JPoint g;      // generator, Montgomery Jacobian (Z = 1_m)
  JPoint gtbl[16];  // window table: gtbl[k] = k*G
  std::once_flag tbl_once;
};

JPoint jac_double(const Curve& c, const JPoint& P) {
  if (P.inf() || is_zero(P.Y)) return JPoint{};
  const Mont& f = c.fp;
  U256 YY = f.sqr(P.Y);
  U256 S = f.mul(P.X, YY);
  S = f.add(S, S);
  S = f.add(S, S);  // 4*X*Y^2
  U256 M;
  if (c.a_zero) {
    U256 XX = f.sqr(P.X);
    M = f.add(f.add(XX, XX), XX);
  } else if (c.a_m3) {
    U256 ZZ = f.sqr(P.Z);
    U256 t = f.mul(f.sub(P.X, ZZ), f.add(P.X, ZZ));
    M = f.add(f.add(t, t), t);
  } else {
    U256 XX = f.sqr(P.X);
    U256 ZZ = f.sqr(P.Z);
    M = f.add(f.add(f.add(XX, XX), XX), f.mul(c.a_m, f.sqr(ZZ)));
  }
  JPoint R;
  U256 MM = f.sqr(M);
  R.X = f.sub(MM, f.add(S, S));
  U256 YYYY = f.sqr(YY);
  U256 y8 = f.add(YYYY, YYYY);
  y8 = f.add(y8, y8);
  y8 = f.add(y8, y8);
  R.Y = f.sub(f.mul(M, f.sub(S, R.X)), y8);
  U256 two_y = f.add(P.Y, P.Y);
  R.Z = f.mul(two_y, P.Z);
  return R;
}

JPoint jac_add(const Curve& c, const JPoint& P, const JPoint& Q) {
  if (P.inf()) return Q;
  if (Q.inf()) return P;
  const Mont& f = c.fp;
  U256 Z1Z1 = f.sqr(P.Z);
  U256 Z2Z2 = f.sqr(Q.Z);
  U256 U1 = f.mul(P.X, Z2Z2);
  U256 U2 = f.mul(Q.X, Z1Z1);
  U256 S1 = f.mul(f.mul(P.Y, Q.Z), Z2Z2);
  U256 S2 = f.mul(f.mul(Q.Y, P.Z), Z1Z1);
  U256 H = f.sub(U2, U1);
  U256 R = f.sub(S2, S1);
  if (is_zero(H)) {
    if (is_zero(R)) return jac_double(c, P);
    return JPoint{};  // P == -Q
  }
  U256 HH = f.sqr(H);
  U256 HHH = f.mul(H, HH);
  U256 V = f.mul(U1, HH);
  JPoint out;
  U256 RR = f.sqr(R);
  out.X = f.sub(f.sub(RR, HHH), f.add(V, V));
  out.Y = f.sub(f.mul(R, f.sub(V, out.X)), f.mul(S1, HHH));
  out.Z = f.mul(f.mul(P.Z, Q.Z), H);
  return out;
}

void build_gtbl(Curve& c) {
  c.gtbl[0] = JPoint{};
  c.gtbl[1] = c.g;
  for (int k = 2; k < 16; ++k) c.gtbl[k] = jac_add(c, c.gtbl[k - 1], c.g);
}

// k1*G + k2*Q, 4-bit windows, MSB-first (k1/k2 plain canonical mod n)
JPoint shamir(Curve& c, const U256& k1, const U256& k2, const JPoint& Q) {
  std::call_once(c.tbl_once, build_gtbl, c);
  JPoint tq[16];
  tq[0] = JPoint{};
  tq[1] = Q;
  for (int k = 2; k < 16; ++k) tq[k] = jac_add(c, tq[k - 1], Q);
  JPoint acc{};
  for (int d = 63; d >= 0; --d) {
    for (int i = 0; i < 4; ++i) acc = jac_double(c, acc);
    unsigned d1 = (k1.w[d / 16] >> ((d % 16) * 4)) & 0xF;
    unsigned d2 = (k2.w[d / 16] >> ((d % 16) * 4)) & 0xF;
    if (d1) acc = jac_add(c, acc, c.gtbl[d1]);
    if (d2) acc = jac_add(c, acc, tq[d2]);
  }
  return acc;
}

// affine x (plain) of P; false when infinity
bool affine(const Curve& c, const JPoint& P, U256* x_out, U256* y_out) {
  if (P.inf()) return false;
  const Mont& f = c.fp;
  U256 zi = f.inv(P.Z);
  U256 zi2 = f.sqr(zi);
  if (x_out) *x_out = f.from_mont(f.mul(P.X, zi2));
  if (y_out) *y_out = f.from_mont(f.mul(P.Y, f.mul(zi2, zi)));
  return true;
}

// ---------------------------------------------------------------------------
// curve singletons
// ---------------------------------------------------------------------------

U256 hex_u256(const char* h) {  // 64 hex chars, big-endian
  uint8_t b[32];
  for (int i = 0; i < 32; ++i) {
    auto nib = [](char ch) -> uint8_t {
      return ch <= '9' ? ch - '0' : (ch | 32) - 'a' + 10;
    };
    b[i] = (uint8_t)((nib(h[2 * i]) << 4) | nib(h[2 * i + 1]));
  }
  return from_be(b);
}

Curve* make_curve(const char* p, const char* n, const char* a, const char* b,
                  const char* gx, const char* gy) {
  Curve* c = new Curve();
  c->fp.init(hex_u256(p));
  c->fn.init(hex_u256(n));
  U256 av = hex_u256(a);
  c->a_zero = is_zero(av);
  U256 p3;
  U256 three;
  three.w[0] = 3;
  sub_bb(c->fp.mod, three, p3);
  c->a_m3 = cmp(av, p3) == 0;
  c->a_m = c->fp.to_mont(av);
  c->b_m = c->fp.to_mont(hex_u256(b));
  // (p+1)/4
  U256 p1 = c->fp.mod;
  U256 one;
  one.w[0] = 1;
  add_cc(p1, one, p1);  // p odd, no overflow past 2^256 for our primes? p+1
  // shift right 2
  for (int s = 0; s < 2; ++s) {
    for (int i = 0; i < 3; ++i)
      p1.w[i] = (p1.w[i] >> 1) | (p1.w[i + 1] << 63);
    p1.w[3] >>= 1;
  }
  c->sqrt_e = p1;
  c->g.X = c->fp.to_mont(hex_u256(gx));
  c->g.Y = c->fp.to_mont(hex_u256(gy));
  c->g.Z = c->fp.one_m;
  return c;
}

Curve& secp256k1() {
  static Curve* c = make_curve(
      "fffffffffffffffffffffffffffffffffffffffffffffffffffffffefffffc2f",
      "fffffffffffffffffffffffffffffffebaaedce6af48a03bbfd25e8cd0364141",
      "0000000000000000000000000000000000000000000000000000000000000000",
      "0000000000000000000000000000000000000000000000000000000000000007",
      "79be667ef9dcbbac55a06295ce870b07029bfcdb2dce28d959f2815b16f81798",
      "483ada7726a3c4655da4fbfc0e1108a8fd17b448a68554199c47d08ffb10d4b8");
  return *c;
}

Curve& sm2p256v1() {
  static Curve* c = make_curve(
      "fffffffeffffffffffffffffffffffffffffffff00000000ffffffffffffffff",
      "fffffffeffffffffffffffffffffffff7203df6b21c6052b53bbf40939d54123",
      "fffffffeffffffffffffffffffffffffffffffff00000000fffffffffffffffc",
      "28e9fa9e9d9f5e344d5a9e4bcf6509a7f39789f515ab8f92ddbcbd414d940e93",
      "32c4ae2c1f1981195f9904466a39c9948fe30bbff2660be1715a4589334c74c7",
      "bc3736a2f4f6779c59bdcee36b692153d0a9877cc62a474002df32e52139f0a0");
  return *c;
}

Curve& by_id(int id) { return id == 0 ? secp256k1() : sm2p256v1(); }

// shared checks: 1 <= r,s < n
bool scalar_ok(const Curve& c, const U256& r, const U256& s) {
  return !is_zero(r) && !is_zero(s) && cmp(r, c.fn.mod) < 0 &&
         cmp(s, c.fn.mod) < 0;
}

// pub (plain, implicitly reduced mod p like the oracle) -> Montgomery
// Jacobian; false when not on the curve
bool load_pub(Curve& c, const U256& qx, const U256& qy, JPoint* out) {
  U256 x = c.fp.reduce(qx), y = c.fp.reduce(qy);
  U256 xm = c.fp.to_mont(x), ym = c.fp.to_mont(y);
  U256 rhs = c.fp.add(c.fp.mul(c.fp.sqr(xm), xm), c.b_m);
  if (!c.a_zero) rhs = c.fp.add(rhs, c.fp.mul(c.a_m, xm));
  if (cmp(c.fp.sqr(ym), rhs) != 0) return false;
  out->X = xm;
  out->Y = ym;
  out->Z = c.fp.one_m;
  return true;
}

// x (affine plain, < p) mod n — p < 2n for both curves
U256 mod_n(const Curve& c, const U256& x) {
  if (cmp(x, c.fn.mod) >= 0) {
    U256 r;
    sub_bb(x, c.fn.mod, r);
    return r;
  }
  return x;
}

}  // namespace

extern "C" {

int ncrypto_available(void) { return 1; }

#ifndef FBTPU_SRC_HASH
#define FBTPU_SRC_HASH "unstamped"
#endif
// sha256 of the source this binary was built from (see native/Makefile);
// Python loaders compare against the checked-in .cpp and refuse a
// drifted binary so stale consensus-critical semantics fail loudly
const char* ncrypto_src_hash(void) { return FBTPU_SRC_HASH; }


// All arrays are count rows of 32 big-endian bytes; ok_out: count bytes.
void ncrypto_ecdsa_verify_batch(int curve_id, uint64_t count,
                                const uint8_t* es, const uint8_t* rs,
                                const uint8_t* ss, const uint8_t* qxs,
                                const uint8_t* qys, uint8_t* ok_out) {
  Curve& c = by_id(curve_id);
  for (uint64_t i = 0; i < count; ++i) {
    ok_out[i] = 0;
    U256 r = from_be(rs + 32 * i), s = from_be(ss + 32 * i);
    if (!scalar_ok(c, r, s)) continue;
    JPoint Q;
    if (!load_pub(c, from_be(qxs + 32 * i), from_be(qys + 32 * i), &Q))
      continue;
    U256 e = mod_n(c, c.fn.reduce(from_be(es + 32 * i)));
    U256 w = c.fn.inv(c.fn.to_mont(s));
    U256 u1 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(e), w));
    U256 u2 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(r), w));
    JPoint R = shamir(c, u1, u2, Q);
    U256 x;
    if (!affine(c, R, &x, nullptr)) continue;
    ok_out[i] = cmp(mod_n(c, x), r) == 0;
  }
}

// vs: count bytes (recovery ids); pub_out: count rows of 64 bytes (x|y).
void ncrypto_ecdsa_recover_batch(int curve_id, uint64_t count,
                                 const uint8_t* es, const uint8_t* rs,
                                 const uint8_t* ss, const uint8_t* vs,
                                 uint8_t* pub_out, uint8_t* ok_out) {
  Curve& c = by_id(curve_id);
  for (uint64_t i = 0; i < count; ++i) {
    ok_out[i] = 0;
    memset(pub_out + 64 * i, 0, 64);
    U256 r = from_be(rs + 32 * i), s = from_be(ss + 32 * i);
    uint8_t v = vs[i];
    if (!scalar_ok(c, r, s)) continue;
    if ((v >> 1) >= 2) continue;  // x = r + (v>>1)*n >= 2n > p
    U256 x = r;
    if (v >> 1) {
      if (add_cc(r, c.fn.mod, x)) continue;  // overflowed 2^256
    }
    if (cmp(x, c.fp.mod) >= 0) continue;
    U256 xm = c.fp.to_mont(x);
    U256 ysq = c.fp.add(c.fp.mul(c.fp.sqr(xm), xm), c.b_m);
    if (!c.a_zero) ysq = c.fp.add(ysq, c.fp.mul(c.a_m, xm));
    U256 y = c.fp.pow(ysq, c.sqrt_e);
    if (cmp(c.fp.sqr(y), ysq) != 0) continue;  // non-residue
    U256 y_plain = c.fp.from_mont(y);
    if ((y_plain.w[0] & 1) != (v & 1)) y = c.fp.neg(y);
    U256 e = mod_n(c, c.fn.reduce(from_be(es + 32 * i)));
    U256 rinv = c.fn.inv(c.fn.to_mont(r));
    U256 u1 = c.fn.from_mont(
        c.fn.mul(c.fn.neg(c.fn.to_mont(e)), rinv));  // -e/r mod n
    U256 u2 = c.fn.from_mont(c.fn.mul(c.fn.to_mont(s), rinv));
    JPoint R;
    R.X = xm;
    R.Y = y;
    R.Z = c.fp.one_m;
    JPoint Q = shamir(c, u1, u2, R);
    U256 qx, qy;
    if (!affine(c, Q, &qx, &qy)) continue;
    to_be(qx, pub_out + 64 * i);
    to_be(qy, pub_out + 64 * i + 32);
    ok_out[i] = 1;
  }
}

void ncrypto_sm2_verify_batch(uint64_t count, const uint8_t* es,
                              const uint8_t* rs, const uint8_t* ss,
                              const uint8_t* qxs, const uint8_t* qys,
                              uint8_t* ok_out) {
  Curve& c = sm2p256v1();
  for (uint64_t i = 0; i < count; ++i) {
    ok_out[i] = 0;
    U256 r = from_be(rs + 32 * i), s = from_be(ss + 32 * i);
    if (!scalar_ok(c, r, s)) continue;
    JPoint Q;
    if (!load_pub(c, from_be(qxs + 32 * i), from_be(qys + 32 * i), &Q))
      continue;
    U256 e = mod_n(c, c.fn.reduce(from_be(es + 32 * i)));
    U256 t = c.fn.add(r, s);  // r, s < n: fn.add reduces mod n
    if (is_zero(t)) continue;
    JPoint P = shamir(c, s, t, Q);
    U256 x;
    if (!affine(c, P, &x, nullptr)) continue;
    // (e + x) mod n == r
    U256 lhs = c.fn.add(e, mod_n(c, x));
    ok_out[i] = cmp(lhs, r) == 0;
  }
}

}  // extern "C"
