// nevm — native EVM frame interpreter for fisco-bcos-tpu.
//
// Reference counterpart: /root/reference/bcos-executor/src/vm/ — the
// reference links evmone (VMFactory.h:46-64) behind an EVMC host interface
// (HostContext.cpp). This is the same architecture rebuilt for this
// framework: a C++ interpreter executes ONE call frame's bytecode at native
// speed, and everything that touches chain state (storage, balances, code,
// sub-calls, creates, logs, selfdestruct) goes through a host callback
// table provided by the Python executor, which retains the savepoint /
// revert / precompile / DMC-routing logic unchanged.
//
// Determinism contract: this interpreter must be bit-for-bit equivalent to
// fisco_bcos_tpu/executor/evm.py::EVM._run — including its documented
// deviations from mainnet (flat warm gas costs, PUSH-past-end semantics,
// JUMP landing at dest+1 so JUMPDEST's 1 gas is skipped) — so a chain can
// mix native and pure-Python executors freely. Any change here must land in
// evm.py too, and vice versa; tests/test_nevm.py diffs the two paths
// opcode family by opcode family.
//
// ABI (ctypes): nevm_execute() + NevmHost callback table + NevmResult.
// Callback buffers (code / call output) must stay valid until the NEXT
// callback or return; the interpreter copies them immediately.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// u256: little-endian 4x64 limbs
// ---------------------------------------------------------------------------

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};

  static U256 from_u64(uint64_t v) {
    U256 r;
    r.w[0] = v;
    return r;
  }
  static U256 from_be(const uint8_t* b, size_t n) {  // big-endian bytes
    U256 r;
    for (size_t i = 0; i < n && i < 32; ++i) {
      size_t bit = (n - 1 - i) * 8;
      r.w[bit / 64] |= (uint64_t)b[i] << (bit % 64);
    }
    return r;
  }
  void to_be(uint8_t out[32]) const {
    for (int i = 0; i < 32; ++i)
      out[i] = (uint8_t)(w[(31 - i) / 8] >> (((31 - i) % 8) * 8));
  }
  bool is_zero() const { return !(w[0] | w[1] | w[2] | w[3]); }
  uint64_t low64() const { return w[0]; }
  bool fits_u64() const { return !(w[1] | w[2] | w[3]); }
  int bit_length() const {
    for (int i = 3; i >= 0; --i)
      if (w[i]) return i * 64 + (64 - __builtin_clzll(w[i]));
    return 0;
  }
  bool bit(int i) const { return (w[i / 64] >> (i % 64)) & 1; }
  void set_bit(int i) { w[i / 64] |= (uint64_t)1 << (i % 64); }
};

static inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

static inline U256 add(const U256& a, const U256& b, uint64_t* carry_out = nullptr) {
  U256 r;
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 s = (unsigned __int128)a.w[i] + b.w[i] + c;
    r.w[i] = (uint64_t)s;
    c = s >> 64;
  }
  if (carry_out) *carry_out = (uint64_t)c;
  return r;
}

static inline U256 sub(const U256& a, const U256& b, uint64_t* borrow_out = nullptr) {
  U256 r;
  unsigned __int128 br = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a.w[i] - b.w[i] - br;
    r.w[i] = (uint64_t)d;
    br = (d >> 64) ? 1 : 0;
  }
  if (borrow_out) *borrow_out = (uint64_t)br;
  return r;
}

static inline U256 mul(const U256& a, const U256& b) {  // low 256 bits
  U256 r;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; i + j < 4; ++j) {
      unsigned __int128 cur =
          (unsigned __int128)a.w[i] * b.w[j] + r.w[i + j] + carry;
      r.w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  return r;
}

static inline U256 shl(const U256& a, unsigned s) {
  U256 r;
  if (s >= 256) return r;
  unsigned limb = s / 64, off = s % 64;
  for (int i = 3; i >= 0; --i) {
    uint64_t v = 0;
    int src = i - (int)limb;
    if (src >= 0) v = a.w[src] << off;
    if (off && src - 1 >= 0) v |= a.w[src - 1] >> (64 - off);
    r.w[i] = v;
  }
  return r;
}

static inline U256 shr(const U256& a, unsigned s) {
  U256 r;
  if (s >= 256) return r;
  unsigned limb = s / 64, off = s % 64;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    unsigned src = i + limb;
    if (src < 4) v = a.w[src] >> off;
    if (off && src + 1 < 4) v |= a.w[src + 1] << (64 - off);
    r.w[i] = v;
  }
  return r;
}

// binary long division: returns quotient, sets rem
static U256 divmod(const U256& a, const U256& b, U256* rem) {
  U256 q, r;
  if (b.is_zero()) {
    if (rem) *rem = U256();
    return q;
  }
  int n = a.bit_length();
  for (int i = n - 1; i >= 0; --i) {
    r = shl(r, 1);
    if (a.bit(i)) r.w[0] |= 1;
    if (cmp(r, b) >= 0) {
      r = sub(r, b);
      q.set_bit(i);
    }
  }
  if (rem) *rem = r;
  return q;
}

static U256 addmod(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 ra, rb, rem;
  divmod(a, n, &ra);
  divmod(b, n, &rb);
  uint64_t carry;
  U256 s = add(ra, rb, &carry);
  // ra, rb < n <= 2^256-1; sum < 2n: one conditional subtract (carry means
  // the 257-bit value >= 2^256 > n, so subtract always applies then)
  if (carry || cmp(s, n) >= 0) s = sub(s, n);
  return s;
}

static U256 mulmod_(const U256& a, const U256& b, const U256& n) {
  if (n.is_zero()) return U256();
  U256 acc;  // double-and-add: acc = a*b mod n without a 512-bit product
  U256 base, rem;
  divmod(a, n, &base);
  for (int i = b.bit_length() - 1; i >= 0; --i) {
    acc = addmod(acc, acc, n);
    if (b.bit(i)) acc = addmod(acc, base, n);
  }
  return acc;
}

static U256 exp_mod2_256(const U256& a, const U256& e) {
  U256 r = U256::from_u64(1);
  U256 base = a;
  int n = e.bit_length();
  for (int i = 0; i < n; ++i) {
    if (e.bit(i)) r = mul(r, base);
    base = mul(base, base);
  }
  return r;
}

static inline bool sign_bit(const U256& v) { return v.w[3] >> 63; }
static inline U256 neg(const U256& v) {
  U256 zero;
  return sub(zero, v);
}

// ---------------------------------------------------------------------------
// Keccak-256 + SM3 (the two CryptoSuite hash flavors)
// ---------------------------------------------------------------------------

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl64(uint64_t x, int s) {
  return (x << s) | (x >> (64 - s));
}

static void keccak_f(uint64_t st[25]) {
  static const int R[24] = {1,  3,  6,  10, 15, 21, 28, 36, 45, 55, 2,  14,
                            27, 41, 56, 8,  25, 43, 62, 18, 39, 61, 20, 44};
  static const int P[24] = {10, 7,  11, 17, 18, 3,  5,  16, 8,  21, 24, 4,
                            15, 23, 19, 13, 12, 2,  20, 14, 22, 9,  6,  1};
  for (int round = 0; round < 24; ++round) {
    uint64_t bc[5];
    for (int i = 0; i < 5; ++i)
      bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
    for (int i = 0; i < 5; ++i) {
      uint64_t t = bc[(i + 4) % 5] ^ rotl64(bc[(i + 1) % 5], 1);
      for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
    }
    uint64_t t = st[1];
    for (int i = 0; i < 24; ++i) {
      uint64_t tmp = st[P[i]];
      st[P[i]] = rotl64(t, R[i]);
      t = tmp;
    }
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; ++i) bc[i] = st[j + i];
      for (int i = 0; i < 5; ++i)
        st[j + i] ^= (~bc[(i + 1) % 5]) & bc[(i + 2) % 5];
    }
    st[0] ^= KECCAK_RC[round];
  }
}

static void keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint64_t st[25] = {0};
  const size_t rate = 136;
  uint8_t block[136];
  while (len >= rate) {
    for (size_t i = 0; i < rate / 8; ++i) {
      uint64_t v;
      memcpy(&v, data + i * 8, 8);
      st[i] ^= v;
    }
    keccak_f(st);
    data += rate;
    len -= rate;
  }
  memset(block, 0, rate);
  memcpy(block, data, len);
  block[len] ^= 0x01;
  block[rate - 1] ^= 0x80;
  for (size_t i = 0; i < rate / 8; ++i) {
    uint64_t v;
    memcpy(&v, block + i * 8, 8);
    st[i] ^= v;
  }
  keccak_f(st);
  for (int i = 0; i < 4; ++i) memcpy(out + i * 8, &st[i], 8);
}

static inline uint32_t rotl32(uint32_t x, int s) {
  return (x << s) | (x >> (32 - s));
}

static void sm3(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t v[8] = {0x7380166f, 0x4914b2b9, 0x172442d7, 0xda8a0600,
                   0xa96f30bc, 0x163138aa, 0xe38dee4d, 0xb0fb0e4e};
  size_t total = len;
  std::vector<uint8_t> msg(data, data + len);
  msg.push_back(0x80);
  while (msg.size() % 64 != 56) msg.push_back(0);
  uint64_t bits = (uint64_t)total * 8;
  for (int i = 7; i >= 0; --i) msg.push_back((uint8_t)(bits >> (i * 8)));
  for (size_t off = 0; off < msg.size(); off += 64) {
    uint32_t w[68], w1[64];
    for (int i = 0; i < 16; ++i)
      w[i] = ((uint32_t)msg[off + 4 * i] << 24) |
             ((uint32_t)msg[off + 4 * i + 1] << 16) |
             ((uint32_t)msg[off + 4 * i + 2] << 8) | msg[off + 4 * i + 3];
    for (int i = 16; i < 68; ++i) {
      uint32_t x = w[i - 16] ^ w[i - 9] ^ rotl32(w[i - 3], 15);
      x = x ^ rotl32(x, 15) ^ rotl32(x, 23);
      w[i] = x ^ rotl32(w[i - 13], 7) ^ w[i - 6];
    }
    for (int i = 0; i < 64; ++i) w1[i] = w[i] ^ w[i + 4];
    uint32_t a = v[0], b = v[1], c = v[2], d = v[3], e = v[4], f = v[5],
             g = v[6], h = v[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t t = i < 16 ? 0x79cc4519 : 0x7a879d8a;
      uint32_t ss1 = rotl32(rotl32(a, 12) + e + rotl32(t, i % 32), 7);
      uint32_t ss2 = ss1 ^ rotl32(a, 12);
      uint32_t ff = i < 16 ? (a ^ b ^ c) : ((a & b) | (a & c) | (b & c));
      uint32_t gg = i < 16 ? (e ^ f ^ g) : ((e & f) | ((~e) & g));
      uint32_t tt1 = ff + d + ss2 + w1[i];
      uint32_t tt2 = gg + h + ss1 + w[i];
      d = c;
      c = rotl32(b, 9);
      b = a;
      a = tt1;
      h = g;
      g = rotl32(f, 19);
      f = e;
      e = tt2 ^ rotl32(tt2, 9) ^ rotl32(tt2, 17);
    }
    v[0] ^= a; v[1] ^= b; v[2] ^= c; v[3] ^= d;
    v[4] ^= e; v[5] ^= f; v[6] ^= g; v[7] ^= h;
  }
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = (uint8_t)(v[i] >> 24);
    out[4 * i + 1] = (uint8_t)(v[i] >> 16);
    out[4 * i + 2] = (uint8_t)(v[i] >> 8);
    out[4 * i + 3] = (uint8_t)v[i];
  }
}

// ---------------------------------------------------------------------------
// ABI structs
// ---------------------------------------------------------------------------

extern "C" {

typedef struct {
  void* ctx;
  int32_t (*sload)(void*, const uint8_t slot[32], uint8_t out[32]);
  // -> old_exists (0/1) or <0 on host error; val_zero mirrors v == 0
  int32_t (*sstore)(void*, const uint8_t slot[32], const uint8_t val[32],
                    int32_t val_zero);
  int32_t (*balance)(void*, const uint8_t addr[20], uint8_t out[32]);
  int32_t (*get_code)(void*, const uint8_t addr[20], const uint8_t** code,
                      uint64_t* len);
  int32_t (*do_log)(void*, const uint8_t* topics, int32_t ntopics,
                    const uint8_t* data, uint64_t len);
  // kind: the opcode (0xF1 CALL / 0xF2 CALLCODE / 0xF4 DELEGATECALL /
  // 0xFA STATICCALL). -> 1 success / 0 failure / <0 host error.
  int32_t (*do_call)(void*, int32_t kind, const uint8_t to[20],
                     const uint8_t value[32], const uint8_t* input,
                     uint64_t input_len, int64_t gas, int64_t* gas_left,
                     const uint8_t** out, uint64_t* out_len);
  int32_t (*do_create)(void*, int32_t is_create2, const uint8_t value[32],
                       const uint8_t* init, uint64_t init_len,
                       const uint8_t salt[32], int64_t gas, int64_t* gas_left,
                       const uint8_t** out, uint64_t* out_len,
                       uint8_t addr_out[20]);
  int32_t (*selfdestruct)(void*, const uint8_t heir[20]);
  // EIP-2929 access-set callbacks: the metering logic lives Python-side
  // (one AccessSet per outer tx shared across native+Python frames);
  // these return the gas to charge. surcharge_only: SELFDESTRUCT heir
  // (0 warm / 2600 cold) vs full access cost (100 warm / 2600 cold).
  int32_t (*access_account)(void*, const uint8_t addr[20],
                            int32_t surcharge_only, int64_t* cost_out);
  int32_t (*sload_cost)(void*, const uint8_t slot[32], int64_t* cost_out);
  // net-metered SSTORE gas (EIP-2200/3529); refunds tracked host-side
  int32_t (*sstore_gas)(void*, const uint8_t slot[32],
                        const uint8_t val[32], int32_t val_zero,
                        int64_t* cost_out);
  // EIP-1153 transient storage (per-tx, host-side AccessSet)
  int32_t (*tload)(void*, const uint8_t slot[32], uint8_t out[32]);
  int32_t (*tstore)(void*, const uint8_t slot[32], const uint8_t val[32]);
} NevmHost;

typedef struct {
  uint8_t origin[20];
  uint8_t coinbase[20];
  uint64_t gas_price;
  int64_t block_number;
  int64_t timestamp_ms;
  int64_t gas_limit;
  uint64_t chain_id;
  int32_t sm_crypto;
} NevmEnv;

typedef struct {
  int32_t status;  // 0 ok, 1 revert, 2 oog, 3 evm error, 4 host error
  int64_t gas_left;
  uint8_t* output;
  uint64_t output_len;
  char error[64];
} NevmResult;

}  // extern "C"

// ---------------------------------------------------------------------------
// interpreter
// ---------------------------------------------------------------------------

namespace {

// gas schedule — mirror evm.py exactly
constexpr int64_t G_BASE = 2, G_VERYLOW = 3, G_LOW = 5, G_MID = 8,
                  G_HIGH = 10, G_KECCAK = 30, G_KECCAK_WORD = 6,
                  G_COPY_WORD = 3, G_SLOAD = 100, G_SSTORE_SET = 20000,
                  G_SSTORE_RESET = 2900, G_LOG = 375, G_LOG_TOPIC = 375,
                  G_LOG_DATA = 8, G_CREATE = 32000, G_CALL = 100,
                  G_CALLVALUE = 9000, G_CALLSTIPEND = 2300, G_EXP = 10,
                  G_EXP_BYTE = 50, G_MEMORY = 3, G_BALANCE = 100,
                  G_EXTCODE = 100, G_SELFDESTRUCT = 5000,
                  G_SSTORE_SENTRY = 2300,
                  G_INITCODE_WORD = 2;

struct OutOfGas {};
struct EvmErr {
  const char* msg;
};
struct HostErr {};

struct Frame {
  U256 stack[1024];
  int sp = 0;
  std::vector<uint8_t> mem;
  std::string ret;
  int64_t gas;
  uint64_t pc = 0;

  explicit Frame(int64_t g) : gas(g) {}

  void use_gas(int64_t n) {
    if (n < 0) throw EvmErr{"negative gas"};
    gas -= n;
    if (gas < 0) throw OutOfGas{};
  }
  void push(const U256& v) {
    if (sp >= 1024) throw EvmErr{"stack overflow"};
    stack[sp++] = v;
  }
  U256 pop() {
    if (sp == 0) throw EvmErr{"stack underflow"};
    return stack[--sp];
  }

  static int64_t mem_cost(uint64_t words) {
    return G_MEMORY * (int64_t)words +
           (int64_t)((words * words) / 512);
  }
  // charge + grow for [off, off+size); huge offsets burn out via gas
  void extend(const U256& off_u, const U256& size_u) {
    if (size_u.is_zero()) return;
    if (!off_u.fits_u64() || !size_u.fits_u64()) throw OutOfGas{};
    unsigned __int128 end =
        (unsigned __int128)off_u.low64() + size_u.low64();
    if (end > ((unsigned __int128)1 << 34)) throw OutOfGas{};
    uint64_t e = (uint64_t)end;
    if (e > mem.size()) {
      uint64_t old_words = (mem.size() + 31) / 32;
      uint64_t new_words = (e + 31) / 32;
      use_gas(mem_cost(new_words) - mem_cost(old_words));
      mem.resize(new_words * 32, 0);
    }
  }
  std::string read_mem(const U256& off_u, const U256& size_u) {
    extend(off_u, size_u);
    if (size_u.is_zero()) return std::string();
    return std::string((const char*)mem.data() + off_u.low64(),
                       size_u.low64());
  }
  void write_mem(const U256& off_u, const uint8_t* data, uint64_t n) {
    if (n == 0) return;
    U256 sz = U256::from_u64(n);
    extend(off_u, sz);
    memcpy(mem.data() + off_u.low64(), data, n);
  }
};

inline void addr_of(const U256& v, uint8_t out[20]) {
  uint8_t full[32];
  v.to_be(full);
  memcpy(out, full + 12, 20);
}

// overflow-safe (n+31)/32: the naive form wraps to 0 for n > 2^64-32,
// silently undercharging copy gas for adversarial sizes
inline uint64_t words32(uint64_t n) { return n / 32 + (n % 32 != 0); }

constexpr uint64_t MEM_CAP = 1ULL << 34;  // lockstep with Frame::extend

// attacker-chosen size feeding a gas multiply: anything beyond the memory
// cap can never be paid for or materialised — out-of-gas before any charge
// or allocation, which also keeps per*size products inside int64
// (lockstep with evm.py _gas_size)
inline uint64_t checked_size(const U256& n_u) {
  if (!n_u.fits_u64() || n_u.low64() > MEM_CAP) throw OutOfGas{};
  return n_u.low64();
}

// code/calldata slice with Python's `buf[s:s+n].ljust(n, b"\0")` semantics
std::string py_slice_pad(const uint8_t* buf, uint64_t len, const U256& s_u,
                         uint64_t n) {
  std::string out(n, '\0');
  if (s_u.fits_u64()) {
    uint64_t s = s_u.low64();
    if (s < len) {
      uint64_t take = len - s < n ? len - s : n;
      memcpy(out.data(), buf + s, take);
    }
  }
  return out;
}

}  // namespace

extern "C" {

void nevm_free(uint8_t* p) { delete[] p; }

#ifndef FBTPU_SRC_HASH
#define FBTPU_SRC_HASH "unstamped"
#endif
// sha256 of the source this binary was built from (see native/Makefile);
// Python loaders compare against the checked-in .cpp and refuse a
// drifted binary so stale consensus-critical semantics fail loudly
const char* nevm_src_hash(void) { return FBTPU_SRC_HASH; }


// standalone hash entry points: the host-path CryptoSuite hashing
// (tx/header hashes, address derivation) routes here when the library is
// loadable — ~100x the pure-Python reference implementation it mirrors
void nevm_keccak256(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  keccak256(data, len, out);
}

void nevm_sm3(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  sm3(data, len, out);
}

// batched hashing over a flattened buffer: offsets[count+1] delimits the
// messages (offsets[0] == 0, offsets[count] == total length). One FFI
// crossing instead of one per message — the per-call ctypes overhead
// (~9 us) was nearly half the cost of the host ingest hashing plane.
void nevm_keccak256_batch(const uint8_t* data, const uint64_t* offsets,
                          uint64_t count, uint8_t* out) {
  for (uint64_t i = 0; i < count; ++i)
    keccak256(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
}

void nevm_sm3_batch(const uint8_t* data, const uint64_t* offsets,
                    uint64_t count, uint8_t* out) {
  for (uint64_t i = 0; i < count; ++i)
    sm3(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
}

int32_t nevm_execute(const NevmHost* host, const NevmEnv* env,
                     const uint8_t* code, uint64_t code_len,
                     const uint8_t* jd_bitmap, const uint8_t* calldata,
                     uint64_t calldata_len, const uint8_t caller[20],
                     const uint8_t address[20], const uint8_t value32[32],
                     int64_t gas, int32_t static_flag, NevmResult* res) {
  Frame f(gas);
  U256 value = U256::from_be(value32, 32);
  auto hash_fn = env->sm_crypto ? sm3 : keccak256;

  auto finish = [&](int32_t status, const std::string& out,
                    int64_t gas_left, const char* err) {
    res->status = status;
    res->gas_left = gas_left;
    res->output_len = out.size();
    if (!out.empty()) {
      res->output = new uint8_t[out.size()];
      memcpy(res->output, out.data(), out.size());
    } else {
      res->output = nullptr;
    }
    snprintf(res->error, sizeof(res->error), "%s", err ? err : "");
    return status;
  };
  auto hostcheck = [](int32_t rc) {
    if (rc < 0) throw HostErr{};
    return rc;
  };

  try {
    while (f.pc < code_len) {
      uint64_t op_pc = f.pc;
      uint8_t op = code[f.pc++];

      // PUSH0..PUSH32
      if (op >= 0x5F && op <= 0x7F) {
        unsigned n = op - 0x5F;
        f.use_gas(n == 0 ? G_BASE : G_VERYLOW);
        uint64_t avail = code_len - f.pc;
        uint64_t take = n < avail ? n : avail;
        // Python's int.from_bytes(code[pc:pc+n]): value of the REMAINING
        // slice (not right-zero-padded) — mirrored deliberately
        f.push(U256::from_be(code + f.pc, take));
        f.pc += n;
        if (f.pc > code_len) f.pc = code_len;
        continue;
      }
      if (op >= 0x80 && op <= 0x8F) {  // DUP1..16
        f.use_gas(G_VERYLOW);
        int n = op - 0x7F;
        if (f.sp < n) throw EvmErr{"stack underflow"};
        f.push(f.stack[f.sp - n]);
        continue;
      }
      if (op >= 0x90 && op <= 0x9F) {  // SWAP1..16
        f.use_gas(G_VERYLOW);
        int n = op - 0x8F;
        if (f.sp < n + 1) throw EvmErr{"stack underflow"};
        std::swap(f.stack[f.sp - 1], f.stack[f.sp - n - 1]);
        continue;
      }

      switch (op) {
        case 0x00:  // STOP
          return finish(0, "", f.gas, nullptr);
        case 0x01: {  // ADD
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          f.push(add(a, b));
          break;
        }
        case 0x02: {  // MUL
          f.use_gas(G_LOW);
          U256 a = f.pop(), b = f.pop();
          f.push(mul(a, b));
          break;
        }
        case 0x03: {  // SUB
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          f.push(sub(a, b));
          break;
        }
        case 0x04: {  // DIV
          f.use_gas(G_LOW);
          U256 a = f.pop(), b = f.pop();
          f.push(b.is_zero() ? U256() : divmod(a, b, nullptr));
          break;
        }
        case 0x05: {  // SDIV
          f.use_gas(G_LOW);
          U256 a = f.pop(), b = f.pop();
          if (b.is_zero()) {
            f.push(U256());
          } else {
            bool na = sign_bit(a), nb = sign_bit(b);
            U256 ua = na ? neg(a) : a, ub = nb ? neg(b) : b;
            U256 q = divmod(ua, ub, nullptr);
            f.push(na != nb ? neg(q) : q);
          }
          break;
        }
        case 0x06: {  // MOD
          f.use_gas(G_LOW);
          U256 a = f.pop(), b = f.pop(), r;
          if (b.is_zero()) {
            f.push(U256());
          } else {
            divmod(a, b, &r);
            f.push(r);
          }
          break;
        }
        case 0x07: {  // SMOD: sign of dividend (Python: abs%abs * sign(a))
          f.use_gas(G_LOW);
          U256 a = f.pop(), b = f.pop(), r;
          if (b.is_zero()) {
            f.push(U256());
          } else {
            bool na = sign_bit(a);
            U256 ua = na ? neg(a) : a, ub = sign_bit(b) ? neg(b) : b;
            divmod(ua, ub, &r);
            f.push(na ? neg(r) : r);
          }
          break;
        }
        case 0x08: {  // ADDMOD
          f.use_gas(G_MID);
          U256 a = f.pop(), b = f.pop(), n = f.pop();
          f.push(addmod(a, b, n));
          break;
        }
        case 0x09: {  // MULMOD
          f.use_gas(G_MID);
          U256 a = f.pop(), b = f.pop(), n = f.pop();
          f.push(mulmod_(a, b, n));
          break;
        }
        case 0x0A: {  // EXP
          U256 a = f.pop(), e = f.pop();
          f.use_gas(G_EXP + G_EXP_BYTE * ((e.bit_length() + 7) / 8));
          f.push(exp_mod2_256(a, e));
          break;
        }
        case 0x0B: {  // SIGNEXTEND
          f.use_gas(G_LOW);
          U256 b = f.pop(), x = f.pop();
          if (b.fits_u64() && b.low64() < 31) {
            int bit = 8 * (int)b.low64() + 7;
            if (x.bit(bit)) {
              // set all bits above `bit`
              for (int i = bit + 1; i < 256; ++i) x.set_bit(i);
            } else {
              U256 mask;
              for (int i = 0; i <= bit; ++i) mask.set_bit(i);
              for (int i = 0; i < 4; ++i) x.w[i] &= mask.w[i];
            }
          }
          f.push(x);
          break;
        }
        case 0x10: {  // LT
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          f.push(U256::from_u64(cmp(a, b) < 0));
          break;
        }
        case 0x11: {  // GT
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          f.push(U256::from_u64(cmp(a, b) > 0));
          break;
        }
        case 0x12: {  // SLT
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          bool na = sign_bit(a), nb = sign_bit(b);
          bool lt = na != nb ? na : cmp(a, b) < 0;
          f.push(U256::from_u64(lt));
          break;
        }
        case 0x13: {  // SGT
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          bool na = sign_bit(a), nb = sign_bit(b);
          bool gt = na != nb ? nb : cmp(a, b) > 0;
          f.push(U256::from_u64(gt));
          break;
        }
        case 0x14: {  // EQ
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop();
          f.push(U256::from_u64(cmp(a, b) == 0));
          break;
        }
        case 0x15: {  // ISZERO
          f.use_gas(G_VERYLOW);
          f.push(U256::from_u64(f.pop().is_zero()));
          break;
        }
        case 0x16: {  // AND
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] & b.w[i];
          f.push(r);
          break;
        }
        case 0x17: {  // OR
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] | b.w[i];
          f.push(r);
          break;
        }
        case 0x18: {  // XOR
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] ^ b.w[i];
          f.push(r);
          break;
        }
        case 0x19: {  // NOT
          f.use_gas(G_VERYLOW);
          U256 a = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = ~a.w[i];
          f.push(r);
          break;
        }
        case 0x1A: {  // BYTE
          f.use_gas(G_VERYLOW);
          U256 i_u = f.pop(), x = f.pop();
          if (i_u.fits_u64() && i_u.low64() < 32) {
            uint8_t be[32];
            x.to_be(be);
            f.push(U256::from_u64(be[i_u.low64()]));
          } else {
            f.push(U256());
          }
          break;
        }
        case 0x1B: {  // SHL
          f.use_gas(G_VERYLOW);
          U256 s = f.pop(), v = f.pop();
          f.push((s.fits_u64() && s.low64() < 256)
                     ? shl(v, (unsigned)s.low64())
                     : U256());
          break;
        }
        case 0x1C: {  // SHR
          f.use_gas(G_VERYLOW);
          U256 s = f.pop(), v = f.pop();
          f.push((s.fits_u64() && s.low64() < 256)
                     ? shr(v, (unsigned)s.low64())
                     : U256());
          break;
        }
        case 0x1D: {  // SAR
          f.use_gas(G_VERYLOW);
          U256 s = f.pop(), v = f.pop();
          bool nv = sign_bit(v);
          if (s.fits_u64() && s.low64() < 256) {
            U256 r = shr(v, (unsigned)s.low64());
            if (nv) {  // fill the vacated high bits with ones
              for (int i = 255; i >= 256 - (int)s.low64(); --i) r.set_bit(i);
            }
            f.push(r);
          } else {
            U256 r;
            if (nv)
              for (int i = 0; i < 4; ++i) r.w[i] = ~0ULL;
            f.push(r);
          }
          break;
        }
        case 0x20: {  // KECCAK256 (suite hash: keccak or sm3)
          U256 off = f.pop(), size = f.pop();
          uint64_t n = checked_size(size);
          f.use_gas(G_KECCAK + G_KECCAK_WORD * (int64_t)words32(n));
          std::string data = f.read_mem(off, size);
          uint8_t h[32];
          hash_fn((const uint8_t*)data.data(), data.size(), h);
          f.push(U256::from_be(h, 32));
          break;
        }
        case 0x30:  // ADDRESS
          f.use_gas(G_BASE);
          f.push(U256::from_be(address, 20));
          break;
        case 0x31: {  // BALANCE (EIP-2929 cold/warm)
          uint8_t a20[20], out[32];
          addr_of(f.pop(), a20);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, a20, 0, &ac));
          f.use_gas(ac);
          hostcheck(host->balance(host->ctx, a20, out));
          f.push(U256::from_be(out, 32));
          break;
        }
        case 0x32:  // ORIGIN
          f.use_gas(G_BASE);
          f.push(U256::from_be(env->origin, 20));
          break;
        case 0x33:  // CALLER
          f.use_gas(G_BASE);
          f.push(U256::from_be(caller, 20));
          break;
        case 0x34:  // CALLVALUE
          f.use_gas(G_BASE);
          f.push(value);
          break;
        case 0x35: {  // CALLDATALOAD
          f.use_gas(G_VERYLOW);
          U256 off = f.pop();
          std::string w = py_slice_pad(calldata, calldata_len, off, 32);
          f.push(U256::from_be((const uint8_t*)w.data(), 32));
          break;
        }
        case 0x36:  // CALLDATASIZE
          f.use_gas(G_BASE);
          f.push(U256::from_u64(calldata_len));
          break;
        case 0x37: {  // CALLDATACOPY
          U256 d = f.pop(), s = f.pop(), n_u = f.pop();
          uint64_t n = checked_size(n_u);
          f.use_gas(G_VERYLOW + G_COPY_WORD * (int64_t)words32(n));
          std::string blob = py_slice_pad(calldata, calldata_len, s, n);
          f.write_mem(d, (const uint8_t*)blob.data(), n);
          break;
        }
        case 0x38:  // CODESIZE
          f.use_gas(G_BASE);
          f.push(U256::from_u64(code_len));
          break;
        case 0x39: {  // CODECOPY
          U256 d = f.pop(), s = f.pop(), n_u = f.pop();
          uint64_t n = checked_size(n_u);
          f.use_gas(G_VERYLOW + G_COPY_WORD * (int64_t)words32(n));
          std::string blob = py_slice_pad(code, code_len, s, n);
          f.write_mem(d, (const uint8_t*)blob.data(), n);
          break;
        }
        case 0x3A:  // GASPRICE
          f.use_gas(G_BASE);
          f.push(U256::from_u64(env->gas_price));
          break;
        case 0x3B: {  // EXTCODESIZE
          uint8_t a20[20];
          addr_of(f.pop(), a20);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, a20, 0, &ac));
          f.use_gas(ac);
          const uint8_t* c = nullptr;
          uint64_t n = 0;
          hostcheck(host->get_code(host->ctx, a20, &c, &n));
          f.push(U256::from_u64(n));
          break;
        }
        case 0x3C: {  // EXTCODECOPY
          uint8_t a20[20];
          addr_of(f.pop(), a20);
          U256 d = f.pop(), s = f.pop(), n_u = f.pop();
          uint64_t n = checked_size(n_u);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, a20, 0, &ac));
          f.use_gas(ac + G_COPY_WORD * (int64_t)words32(n));
          const uint8_t* c = nullptr;
          uint64_t clen = 0;
          hostcheck(host->get_code(host->ctx, a20, &c, &clen));
          std::string blob = py_slice_pad(c, clen, s, n);
          f.write_mem(d, (const uint8_t*)blob.data(), n);
          break;
        }
        case 0x3D:  // RETURNDATASIZE
          f.use_gas(G_BASE);
          f.push(U256::from_u64(f.ret.size()));
          break;
        case 0x3E: {  // RETURNDATACOPY
          U256 d = f.pop(), s = f.pop(), n_u = f.pop();
          uint64_t n = checked_size(n_u);
          f.use_gas(G_VERYLOW + G_COPY_WORD * (int64_t)words32(n));
          // overflow-safe bounds: s + n > len without wrapping uint64
          if (!s.fits_u64() ||
              s.low64() > f.ret.size() || n > f.ret.size() - s.low64())
            throw EvmErr{"returndata out of bounds"};
          f.write_mem(d, (const uint8_t*)f.ret.data() + s.low64(), n);
          break;
        }
        case 0x3F: {  // EXTCODEHASH
          uint8_t a20[20];
          addr_of(f.pop(), a20);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, a20, 0, &ac));
          f.use_gas(ac);
          const uint8_t* c = nullptr;
          uint64_t n = 0;
          hostcheck(host->get_code(host->ctx, a20, &c, &n));
          if (n == 0) {
            f.push(U256());
          } else {
            uint8_t h[32];
            hash_fn(c, n, h);
            f.push(U256::from_be(h, 32));
          }
          break;
        }
        case 0x40:  // BLOCKHASH (not tracked: zero)
          f.use_gas(20);
          f.pop();
          f.push(U256());
          break;
        case 0x41:  // COINBASE
          f.use_gas(G_BASE);
          f.push(U256::from_be(env->coinbase, 20));
          break;
        case 0x42:  // TIMESTAMP (seconds)
          f.use_gas(G_BASE);
          f.push(U256::from_u64((uint64_t)(env->timestamp_ms / 1000)));
          break;
        case 0x43:  // NUMBER
          f.use_gas(G_BASE);
          f.push(U256::from_u64((uint64_t)env->block_number));
          break;
        case 0x44:  // PREVRANDAO (deterministic chain: 0)
          f.use_gas(G_BASE);
          f.push(U256());
          break;
        case 0x45:  // GASLIMIT
          f.use_gas(G_BASE);
          f.push(U256::from_u64((uint64_t)env->gas_limit));
          break;
        case 0x46:  // CHAINID
          f.use_gas(G_BASE);
          f.push(U256::from_u64(env->chain_id));
          break;
        case 0x47: {  // SELFBALANCE
          f.use_gas(G_LOW);
          uint8_t out[32];
          hostcheck(host->balance(host->ctx, address, out));
          f.push(U256::from_be(out, 32));
          break;
        }
        case 0x48:  // BASEFEE
          f.use_gas(G_BASE);
          f.push(U256());
          break;
        case 0x50:  // POP
          f.use_gas(G_BASE);
          f.pop();
          break;
        case 0x51: {  // MLOAD
          f.use_gas(G_VERYLOW);
          U256 off = f.pop();
          std::string w = f.read_mem(off, U256::from_u64(32));
          f.push(U256::from_be((const uint8_t*)w.data(), 32));
          break;
        }
        case 0x52: {  // MSTORE
          f.use_gas(G_VERYLOW);
          U256 off = f.pop(), v = f.pop();
          uint8_t be[32];
          v.to_be(be);
          f.write_mem(off, be, 32);
          break;
        }
        case 0x53: {  // MSTORE8
          f.use_gas(G_VERYLOW);
          U256 off = f.pop(), v = f.pop();
          uint8_t b = (uint8_t)(v.w[0] & 0xFF);
          f.write_mem(off, &b, 1);
          break;
        }
        case 0x54: {  // SLOAD (EIP-2929 cold/warm)
          uint8_t slot[32], out[32] = {0};
          f.pop().to_be(slot);
          int64_t sc = 0;
          hostcheck(host->sload_cost(host->ctx, slot, &sc));
          f.use_gas(sc);
          int32_t exists = hostcheck(host->sload(host->ctx, slot, out));
          f.push(exists ? U256::from_be(out, 32) : U256());
          break;
        }
        case 0x55: {  // SSTORE (EIP-2200 net metering + EIP-3529)
          if (static_flag) throw EvmErr{"SSTORE in static call"};
          if (f.gas <= G_SSTORE_SENTRY) throw OutOfGas{};
          U256 slot_u = f.pop(), v = f.pop();
          uint8_t slot[32], val[32];
          slot_u.to_be(slot);
          v.to_be(val);
          int vz = v.is_zero();
          int64_t sc = 0;
          hostcheck(host->sstore_gas(host->ctx, slot, val, vz, &sc));
          f.use_gas(sc);
          hostcheck(host->sstore(host->ctx, slot, val, vz));
          break;
        }
        case 0x56: {  // JUMP
          f.use_gas(G_MID);
          U256 d = f.pop();
          if (!d.fits_u64() || d.low64() >= code_len ||
              !(jd_bitmap[d.low64() / 8] >> (d.low64() % 8) & 1))
            throw EvmErr{"bad jump destination"};
          f.pc = d.low64() + 1;  // mirror evm.py: lands past the JUMPDEST
          break;
        }
        case 0x57: {  // JUMPI
          f.use_gas(G_HIGH);
          U256 d = f.pop(), c = f.pop();
          if (!c.is_zero()) {
            if (!d.fits_u64() || d.low64() >= code_len ||
                !(jd_bitmap[d.low64() / 8] >> (d.low64() % 8) & 1))
              throw EvmErr{"bad jump destination"};
            f.pc = d.low64() + 1;
          }
          break;
        }
        case 0x58:  // PC
          f.use_gas(G_BASE);
          f.push(U256::from_u64(op_pc));
          break;
        case 0x59:  // MSIZE
          f.use_gas(G_BASE);
          f.push(U256::from_u64(f.mem.size()));
          break;
        case 0x5A:  // GAS
          f.use_gas(G_BASE);
          f.push(U256::from_u64((uint64_t)f.gas));
          break;
        case 0x5B:  // JUMPDEST
          f.use_gas(1);
          break;
        case 0x5C: {  // TLOAD (EIP-1153)
          f.use_gas(G_SLOAD);
          uint8_t slot[32], out[32] = {0};
          f.pop().to_be(slot);
          hostcheck(host->tload(host->ctx, slot, out));
          f.push(U256::from_be(out, 32));
          break;
        }
        case 0x5D: {  // TSTORE (EIP-1153)
          if (static_flag) throw EvmErr{"TSTORE in static call"};
          f.use_gas(G_SLOAD);
          uint8_t slot[32], val[32];
          f.pop().to_be(slot);
          f.pop().to_be(val);
          hostcheck(host->tstore(host->ctx, slot, val));
          break;
        }
        case 0x5E: {  // MCOPY (EIP-5656), memmove semantics
          U256 d = f.pop(), s = f.pop(), n_u = f.pop();
          uint64_t n = checked_size(n_u);
          f.use_gas(G_VERYLOW + G_COPY_WORD * (int64_t)words32(n));
          if (n) {
            std::string blob = f.read_mem(s, n_u);
            f.write_mem(d, (const uint8_t*)blob.data(), n);
          }
          break;
        }
        case 0xA0:
        case 0xA1:
        case 0xA2:
        case 0xA3:
        case 0xA4: {  // LOG0..LOG4
          if (static_flag) throw EvmErr{"LOG in static call"};
          int ntopics = op - 0xA0;
          U256 off = f.pop(), size = f.pop();
          uint8_t topics[4 * 32];
          for (int i = 0; i < ntopics; ++i) f.pop().to_be(topics + 32 * i);
          uint64_t n = checked_size(size);
          f.use_gas(G_LOG + G_LOG_TOPIC * ntopics +
                    G_LOG_DATA * (int64_t)n);
          std::string data = f.read_mem(off, size);
          hostcheck(host->do_log(host->ctx, topics, ntopics,
                                 (const uint8_t*)data.data(), data.size()));
          break;
        }
        case 0xF0:
        case 0xF5: {  // CREATE / CREATE2
          if (static_flag) throw EvmErr{"CREATE in static call"};
          U256 v = f.pop(), off = f.pop(), size = f.pop();
          uint8_t salt[32] = {0};
          if (op == 0xF5) f.pop().to_be(salt);
          uint64_t n = checked_size(size);
          f.use_gas(G_CREATE + G_INITCODE_WORD * (int64_t)words32(n));
          std::string init = f.read_mem(off, size);
          int64_t gas_child = f.gas - f.gas / 64;
          f.use_gas(gas_child);
          uint8_t val[32];
          v.to_be(val);
          int64_t child_left = 0;
          const uint8_t* out = nullptr;
          uint64_t out_len = 0;
          uint8_t addr20[20] = {0};
          int32_t ok = hostcheck(host->do_create(
              host->ctx, op == 0xF5, val, (const uint8_t*)init.data(),
              init.size(), salt, gas_child, &child_left, &out, &out_len,
              addr20));
          f.gas += child_left;
          f.ret = ok ? std::string()
                     : std::string((const char*)out, out_len);
          f.push(ok ? U256::from_be(addr20, 20) : U256());
          break;
        }
        case 0xF1:
        case 0xF2:
        case 0xF4:
        case 0xFA: {  // CALL / CALLCODE / DELEGATECALL / STATICCALL
          U256 gas_req = f.pop(), to = f.pop();
          U256 v;
          if (op == 0xF1 || op == 0xF2) v = f.pop();
          U256 in_off = f.pop(), in_size = f.pop();
          U256 out_off = f.pop(), out_size = f.pop();
          if (static_flag && !v.is_zero() && op == 0xF1)
            throw EvmErr{"value call in static context"};
          uint8_t to20c[20];
          addr_of(to, to20c);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, to20c, 0, &ac));
          f.use_gas(ac + (v.is_zero() ? 0 : G_CALLVALUE));
          std::string args = f.read_mem(in_off, in_size);
          f.extend(out_off, out_size);
          int64_t avail = f.gas - f.gas / 64;
          int64_t child = (gas_req.fits_u64() &&
                           gas_req.low64() <= (uint64_t)avail)
                              ? (int64_t)gas_req.low64()
                              : avail;
          f.use_gas(child);
          if (!v.is_zero()) child += G_CALLSTIPEND;
          uint8_t to20[20], val[32];
          addr_of(to, to20);
          v.to_be(val);
          int64_t child_left = 0;
          const uint8_t* out = nullptr;
          uint64_t out_len = 0;
          int32_t ok = hostcheck(host->do_call(
              host->ctx, op, to20, val, (const uint8_t*)args.data(),
              args.size(), child, &child_left, &out, &out_len));
          f.gas += child_left;
          f.ret = std::string((const char*)out, out_len);
          uint64_t copy = out_size.fits_u64() && out_size.low64() < out_len
                              ? out_size.low64()
                              : out_len;
          if (copy) f.write_mem(out_off, (const uint8_t*)f.ret.data(), copy);
          f.push(U256::from_u64(ok ? 1 : 0));
          break;
        }
        case 0xF3: {  // RETURN
          U256 off = f.pop(), size = f.pop();
          // sequence the read BEFORE f.gas is observed: read_mem charges
          // memory expansion, and C++ argument evaluation order is
          // unspecified (caught by differential fuzz: 9 gas divergence)
          std::string out = f.read_mem(off, size);
          return finish(0, out, f.gas, nullptr);
        }
        case 0xFD: {  // REVERT
          U256 off = f.pop(), size = f.pop();
          std::string out = f.read_mem(off, size);
          return finish(1, out, f.gas, "revert");
        }
        case 0xFE:
          throw EvmErr{"invalid opcode 0xfe"};
        case 0xFF: {  // SELFDESTRUCT (cold-heir surcharge)
          if (static_flag) throw EvmErr{"SELFDESTRUCT in static call"};
          uint8_t heir[20];
          addr_of(f.pop(), heir);
          int64_t ac = 0;
          hostcheck(host->access_account(host->ctx, heir, 1, &ac));
          f.use_gas(G_SELFDESTRUCT + ac);
          hostcheck(host->selfdestruct(host->ctx, heir));
          return finish(0, "", f.gas, nullptr);
        }
        default:
          throw EvmErr{"unknown opcode"};
      }
    }
    return finish(0, "", f.gas, nullptr);
  } catch (OutOfGas&) {
    return finish(2, "", 0, "out of gas");
  } catch (EvmErr& e) {
    return finish(3, "", 0, e.msg);
  } catch (HostErr&) {
    return finish(4, "", 0, "host error");
  } catch (std::exception& e) {
    // no C++ exception may ever cross the extern-C/ctypes boundary:
    // std::terminate there aborts the whole node process
    return finish(5, "", 0, e.what());
  } catch (...) {
    return finish(5, "", 0, "native internal error");
  }
}

}  // extern "C"
